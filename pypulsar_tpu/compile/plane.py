"""The compilation plane: persistent XLA cache + AOT executable
registry + warm-pool precompile hooks (round 22).

Every ``jax.jit`` outside ``ops/`` leaf kernels dispatches through
:func:`plane_jit` (psrlint PL018 enforces it). The wrapper layers
three caches:

1. **Persistent XLA cache** (``PYPULSAR_TPU_COMPILE_CACHE``, default
   ``~/.cache/pypulsar_tpu/xla``): ``jax_compilation_cache_dir`` wired
   fleet-wide, so a geometry compiled by ANY process on ANY host is a
   disk hit everywhere else. Configured lazily, once per process, the
   first time the plane compiles anything.
2. **In-process AOT executable registry**: per-wrapper executables
   from ``jit(f).lower(...).compile()`` keyed by (stage, static
   argument values, dynamic leaf shapes/dtypes, default device, jax
   version, device kind, resolved tuned-config digest). A repeat
   geometry skips tracing entirely — ``compile.cache_hit`` — and a
   tuned config change (round 17) keys a *different* entry, so tuning
   trials are never charged another trial's first-trace compile.
3. **Warm-pool precompile**: pipeline stages register warmers
   (:func:`register_warmer`); the fleet scheduler's host pool calls
   :func:`warm_stage` for the next ready observation's geometry while
   devices are busy, so a cold fleet's first device dispatch finds a
   warm executable (``wrapper.warm(...)`` lowers from
   ``jax.ShapeDtypeStruct`` — no data needed).

Anything the AOT path cannot key faithfully — tracer inputs (a
plane-wrapped fn called under an outer trace), variadic signatures,
multi-device arrays from a mesh context — falls back to the held
plain ``jax.jit`` and counts ``compile.aot_fallback``; factory sites
that close over meshes/shardings opt out wholesale with ``aot=False``
(the plane still owns their telemetry). A bad cache dir or a failed
AOT dispatch degrades the same way: the plane must never abort work
that plain jit would have completed.

Cross-host accounting: the XLA disk cache is opaque, so on every
in-process miss the plane probes a sidecar marker
(``<cache>/plane/<digest>.json``, written atomically after each
compile, digest excludes process-local identity) and counts
``compile.persistent_hit`` when another process/host compiled that
key first — the counter the multi-host test asserts on.
"""

from __future__ import annotations

import hashlib
import inspect
import itertools
import json
import os
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax

from pypulsar_tpu.compile.registry import (  # noqa: F401  (re-export)
    OPS_LEAF_ALLOWLIST, bucket_rows, bucket_size, buckets_enabled,
)
from pypulsar_tpu.obs import telemetry
from pypulsar_tpu.tune import knobs

__all__ = [
    "plane_jit",
    "PlaneJit",
    "configure_persistent_cache",
    "persistent_cache_dir",
    "note_bucket_pad",
    "register_warmer",
    "warmable_stages",
    "warm_stage",
]


class _Unkeyable(Exception):
    """Inputs the AOT registry cannot key faithfully -> plain jit."""


# ---------------------------------------------------------------------------
# persistent XLA cache

_cache_lock = threading.Lock()
_cache_state: Dict[str, Any] = {"configured": False, "dir": None}


def configure_persistent_cache() -> Optional[str]:
    """Wire ``jax_compilation_cache_dir`` to the fleet-shared directory
    (``PYPULSAR_TPU_COMPILE_CACHE``; ``0``/``off`` disables). Resolved
    once per process — idempotent, thread-safe, returns the active
    directory or None. Never raises: an uncreatable directory simply
    disables persistence (plain in-memory jit still works)."""
    with _cache_lock:
        if _cache_state["configured"]:
            return _cache_state["dir"]
        _cache_state["configured"] = True
    # The jax.config updates below go through JAX's own global config
    # machinery and must not run under our lock; the once-per-process
    # latch above already guarantees a single configuring thread (a
    # concurrent caller may briefly observe dir=None, which only skips
    # the accounting sidecar for that one dispatch).
    raw = knobs.env_str("PYPULSAR_TPU_COMPILE_CACHE")
    if not raw or str(raw).strip().lower() in ("0", "off", "none"):
        return None
    path = os.path.abspath(os.path.expanduser(str(raw)))
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # cache everything: the CPU-toy geometries tests exercise
        # compile in microseconds, and tiny executables are exactly
        # the ones a mixed-geometry fleet recompiles the most
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update(
            "jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update(
            "jax_persistent_cache_enable_xla_caches", "all")
    except Exception:
        return None
    _cache_state["dir"] = path
    return path


def persistent_cache_dir() -> Optional[str]:
    """The active persistent cache directory (configuring lazily)."""
    return configure_persistent_cache()


def _marker_path(digest: str) -> Optional[str]:
    root = _cache_state["dir"] if _cache_state["configured"] \
        else configure_persistent_cache()
    if not root:
        return None
    return os.path.join(root, "plane", f"{digest}.json")


def _probe_marker(digest: str, meta: Dict[str, Any]) -> bool:
    """True when another process already compiled this key (the
    cross-host ``compile.persistent_hit`` probe); records our own
    marker intent in ``meta`` for :func:`_write_marker`."""
    path = _marker_path(digest)
    if path is None:
        return False
    meta["marker_path"] = path
    return os.path.exists(path)


def _write_marker(meta: Dict[str, Any], payload: Dict[str, Any]) -> None:
    path = meta.get("marker_path")
    if not path:
        return
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as fh:
            json.dump(payload, fh, sort_keys=True)
        os.replace(tmp, path)
    except OSError:
        pass  # accounting sidecar only — never worth failing a dispatch


# ---------------------------------------------------------------------------
# keying helpers

def _aot_enabled() -> bool:
    raw = knobs.env_str("PYPULSAR_TPU_COMPILE_AOT")
    return str(raw) not in ("0", "off", "none")


def _device_key() -> str:
    """The thread's placement context: ``jax.default_device`` is
    thread-local (the scheduler sets it per gang lease), and an AOT
    executable is pinned to the device it lowered under — so placement
    MUST key the registry or a lease on chip 3 would silently run on
    chip 0."""
    dd = jax.config.jax_default_device
    return "auto" if dd is None else str(dd)


def _default_device_str() -> str:
    """Where jit lands a host input: the thread's jax.default_device,
    else the backend's first device (cached — process-stable)."""
    dd = jax.config.jax_default_device
    if dd is not None:
        return str(dd)
    d0 = _kind_cache.get("dev0")
    if d0 is None:
        try:
            d0 = str(jax.devices()[0])  # psrlint: ignore[PL002] -- registry-key metadata (jit's implicit placement target), not a compute placement
        except Exception:
            d0 = ""
        _kind_cache["dev0"] = d0
    return d0


def _leaf_key(x: Any) -> Tuple:
    if isinstance(x, jax.core.Tracer):
        raise _Unkeyable("tracer input")
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        if isinstance(x, jax.Array):
            try:
                devs = x.devices()
            except Exception:
                raise _Unkeyable("unreadable placement")
            if len(devs) != 1:
                raise _Unkeyable("multi-device input")
            d = str(next(iter(devs)))
            # an array already sitting where jit would commit a host
            # input keys like a host input — so a ShapeDtypeStruct
            # warm covers both call forms
            return ("a", tuple(shape), str(dtype),
                    "host" if d == _default_device_str() else d)
        return ("a", tuple(shape), str(dtype), "host")
    if isinstance(x, (bool, int, float, complex)) or x is None:
        # python scalars trace to weak-typed arrays: the TYPE picks
        # the dtype, the value never affects the executable
        return ("s", type(x).__name__)
    raise _Unkeyable(f"unkeyable leaf {type(x).__name__}")


def _config_digest(stage: str) -> str:
    """Digest of the stage's fully-resolved knob config (trial > env >
    tuned > default) — the round-17 fix: a tuned config change keys a
    different executable. Round 24 hoisted the digest itself into
    ``knobs.config_digest`` so the batch broker coalesces on the exact
    key the plane compiles under."""
    return knobs.config_digest(stage)


_WRAPPER_IDS = itertools.count()


# ---------------------------------------------------------------------------
# the wrapper

class PlaneJit:
    """Drop-in for ``jax.jit`` that dispatches through the plane's AOT
    executable registry (see module docstring for the cache layers and
    the fallback ladder)."""

    def __init__(self, fn: Callable, *, static_argnames=(),
                 stage: str = "", name: Optional[str] = None,
                 aot: bool = True):
        if isinstance(static_argnames, str):
            static_argnames = (static_argnames,)
        self._fn = fn
        self._static = tuple(static_argnames)
        self._stage = stage
        self.__name__ = name or getattr(fn, "__name__", "fn")
        self._jit = (jax.jit(fn, static_argnames=self._static)
                     if self._static else jax.jit(fn))
        self._uid = next(_WRAPPER_IDS)
        self._compiled: Dict[Tuple, Any] = {}
        self._lock = threading.Lock()
        self._aot = bool(aot)
        try:
            sig = inspect.signature(fn)
        except (TypeError, ValueError):
            sig = None
        if sig is None or any(
                p.kind in (p.VAR_POSITIONAL, p.VAR_KEYWORD)
                for p in sig.parameters.values()):
            self._aot = False  # can't map statics -> positions
        self._sig = sig

    # -- keying ------------------------------------------------------------

    def _split(self, args, kwargs):
        """Bind the call, split static vs dynamic arguments, and build
        the registry key. Returns (key, persist_digest, dynamics)."""
        ba = self._sig.bind(*args, **kwargs)
        ba.apply_defaults()
        statics, dyn_keys, dynamics = [], [], []
        for pname, value in ba.arguments.items():
            if pname in self._static:
                statics.append((pname, repr(value)))
            else:
                leaves, treedef = jax.tree_util.tree_flatten(value)
                dyn_keys.append(
                    (pname, str(treedef),
                     tuple(_leaf_key(leaf) for leaf in leaves)))
                dynamics.append(value)
        shape_key = (tuple(statics), tuple(dyn_keys))
        cfg = _config_digest(self._stage)
        key = (shape_key, _device_key(), cfg)
        blob = repr((self.__name__, self._stage, jax.__version__,
                     _device_kind(), shape_key, cfg)).encode()
        return key, hashlib.sha1(blob).hexdigest(), dynamics

    # -- dispatch ----------------------------------------------------------

    def __call__(self, *args, **kwargs):
        if not self._aot or not _aot_enabled():
            return self._jit(*args, **kwargs)
        try:
            key, digest, dynamics = self._split(args, kwargs)
        except (_Unkeyable, TypeError):
            telemetry.counter("compile.aot_fallback")
            return self._jit(*args, **kwargs)
        with self._lock:
            compiled = self._compiled.get(key)
        if compiled is None:
            compiled = self._compile(key, digest, args, kwargs)
            if compiled is None:  # lowering refused -> plain jit
                return self._jit(*args, **kwargs)
        else:
            telemetry.counter("compile.cache_hit")
        try:
            return compiled(*dynamics)
        except Exception:
            # shape drift inside a pytree, donation mismatch, a
            # backend refusing the AOT path — plain jit still works
            telemetry.counter("compile.aot_fallback")
            return self._jit(*args, **kwargs)

    def _compile(self, key, digest, args, kwargs):
        configure_persistent_cache()
        meta: Dict[str, Any] = {}
        cross_host = _probe_marker(digest, meta)
        label = self._stage or self.__name__
        t0 = time.perf_counter()
        try:
            compiled = self._jit.lower(*args, **kwargs).compile()
        except Exception:
            telemetry.counter("compile.aot_fallback")
            with self._lock:
                self._aot = False  # this fn will never lower; stop trying
            return None
        dt = time.perf_counter() - t0
        telemetry.counter("compile.cache_miss")
        telemetry.counter("compile.ms", dt * 1e3)
        if cross_host:
            telemetry.counter("compile.persistent_hit")
        # first-dispatch span: steady-state hits stay span-free, so
        # tlmsum's compilation roll-up shows first-vs-steady directly
        telemetry.record_span(f"compile.first.{label}", dt)
        _write_marker(meta, {
            "fn": self.__name__, "stage": self._stage,
            "jax": jax.__version__, "device_kind": _device_kind(),
        })
        with self._lock:
            self._compiled.setdefault(key, compiled)
        return compiled

    # -- precompile --------------------------------------------------------

    def warm(self, *args, **kwargs) -> bool:
        """AOT-compile for the given (possibly abstract —
        ``jax.ShapeDtypeStruct``) arguments without dispatching; the
        warm-pool entry point. True when this call compiled (or found
        cross-host), False on a registry hit or fallback."""
        if not self._aot or not _aot_enabled():
            return False
        try:
            key, digest, _ = self._split(args, kwargs)
        except (_Unkeyable, TypeError):
            return False
        with self._lock:
            if key in self._compiled:
                return False
        return self._compile(key, digest, args, kwargs) is not None

    # -- introspection (tests / bench) ------------------------------------

    def cache_size(self) -> int:
        with self._lock:
            return len(self._compiled)


_kind_cache: Dict[str, str] = {}


def _device_kind() -> str:
    """Backend device kind, resolved lazily (touching jax.devices() at
    import would initialize the backend before CLIs pick a platform)."""
    k = _kind_cache.get("kind")
    if k is None:
        try:
            k = jax.devices()[0].device_kind  # psrlint: ignore[PL002] -- cache-key metadata (hardware KIND, not a compute placement); no lease involved
        except Exception:
            k = "unknown"
        _kind_cache["kind"] = k
    return k


def plane_jit(fn: Optional[Callable] = None, *, static_argnames=(),
              stage: str = "", name: Optional[str] = None,
              aot: bool = True):
    """``jax.jit`` through the compilation plane. Usable as a direct
    wrapper (``plane_jit(f, stage="fold")``) or a decorator factory
    (``@plane_jit(static_argnames=("nbins",), stage="fold")``).
    ``aot=False`` keeps plain-jit dispatch (for factories that close
    over meshes/shardings) while still routing through the plane."""
    if fn is None:
        return lambda f: PlaneJit(f, static_argnames=static_argnames,
                                  stage=stage, name=name, aot=aot)
    return PlaneJit(fn, static_argnames=static_argnames, stage=stage,
                    name=name, aot=aot)


def note_bucket_pad(n_real: int, n_padded: int) -> None:
    """Account one bucketing decision: the pad fraction gauge and the
    padded-row counter the bench reads."""
    if n_padded <= 0:
        return
    telemetry.gauge("compile.bucket_pad_frac",
                    (n_padded - n_real) / float(n_padded))
    if n_padded > n_real:
        telemetry.counter("compile.bucket_pad_rows", n_padded - n_real)


# ---------------------------------------------------------------------------
# warm-pool registry

_warmers: Dict[str, Callable[..., int]] = {}
_warmers_lock = threading.Lock()


def register_warmer(stage: str, fn: Callable[..., int]) -> None:
    """Register ``stage``'s precompile planner: ``fn(**geometry)``
    lowers that stage's wrappers for one observation geometry and
    returns how many executables it compiled. Last registration wins
    (re-import safe)."""
    with _warmers_lock:
        _warmers[stage] = fn


def warmable_stages() -> Tuple[str, ...]:
    with _warmers_lock:
        return tuple(sorted(_warmers))


def warm_stage(stage: str, **geometry) -> int:
    """Run ``stage``'s registered warmer for ``geometry``; 0 when no
    warmer is registered or the warmer declined. Never raises — the
    warm pool is an optimization, not a correctness path."""
    with _warmers_lock:
        fn = _warmers.get(stage)
    if fn is None:
        return 0
    try:
        return int(fn(**geometry) or 0)
    except Exception:
        telemetry.counter("compile.warm_error")
        return 0
