"""pypulsar_tpu — a TPU-native pulsar search & timing framework.

A ground-up redesign of the capabilities of `pypulsar` (Patrick Lazarus'
pure-NumPy pulsar toolkit riding on PRESTO; reference at /root/reference)
for JAX/XLA/Pallas on TPU:

- ``core``     : Spectra pytree container + physical constants (replaces the
                 external ``psr_utils`` surface; see SURVEY.md §2.5).
- ``ops``      : pure-JAX kernels (dedisperse / subband / downsample / smooth /
                 scale / mask / zero-DM / detrend / fold / SNR) with NumPy
                 golden twins for parity testing.
- ``parallel`` : (planned) device-mesh DM-trial sweep engine (shard_map over
                 ICI), time-axis sharding with halo exchange, streaming.
- ``io``       : (planned) SIGPROC filterbank / PSRFITS / .dat/.inf/.fft /
                 pulse-text / zaplist / accelcands readers & writers
                 (replaces PRESTO's sigproc/infodata codecs).
- ``plan``     : (planned) dedispersion planning (DDplan equivalent;
                 reference utils/DDplan2b.py).
- ``fourier``  : (planned) power spectra, dereddening, harmonic sums, zapping.
- ``fold``     : (planned) polyco evaluation+generation, pulse profiles, TOAs
                 (FFTFIT equivalent in jnp.fft).
- ``astro``    : (planned) coordinates, time, sky temperature, radiometer SNR.
- ``cli``      : (planned) command-line tools mirroring reference bin/ scripts.
- ``obs``      : structured telemetry (spans / counters / device stats) with a
                 JSONL sink and the ``tlmsum`` summarizer; ``utils.profiling``
                 is a shim over it.
- ``resilience``: failure-handling substrate — OOM-adaptive dispatch
                 halving, journaled size/sha256-validated resume, atomic
                 outputs, deterministic fault injection
                 (docs/ARCHITECTURE.md "Failure model & recovery").
"""

__version__ = "0.1.0"

from pypulsar_tpu.core.spectra import Spectra  # noqa: F401
