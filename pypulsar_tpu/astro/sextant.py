"""Coordinate transforms (parity: reference utils/astro/sextant.py).

Equatorial <-> ecliptic, hadec <-> altaz, equatorial -> galactic, and
B1950 <-> J2000 precession via fixed rotation matrices (slalib-free).
All transforms accept/return units "sexigesimal", "deg", "hour", or "rad"
and dispatch through :mod:`pypulsar_tpu.astro.protractor`.
"""

import numpy as np

from pypulsar_tpu.astro import protractor

# Mean obliquity of the ecliptic (radians)
OBLIQUITY_J2000 = 0.409092804
OBLIQUITY_B1950 = 0.409206212

# Galactic north pole / origin in B1950 equatorial coords (radians)
GAL_POLE_RA_B1950 = 3.35539549
GAL_POLE_DECL_B1950 = 0.478220215
GAL_ORIGIN_L = 5.28834763  # 303 deg

# B1950 -> J2000 rotation (stargazing.net/kepler/b1950.html matrix)
_B1950_TO_J2000 = np.array(
    [
        [0.9999257080, -0.0111789372, -0.0048590035],
        [0.0111789372, 0.9999375134, -0.0000271626],
        [0.0048590036, -0.0000271579, 0.9999881946],
    ]
)
_J2000_TO_B1950 = _B1950_TO_J2000.T


def _in_to_rad(val, units, kind):
    """kind is 'ra'-like (hmsstr when sexigesimal) or 'dec'-like (dmsstr)."""
    if units == "sexigesimal":
        units = "hmsstr" if kind == "ra" else "dmsstr"
    return protractor.convert(val, units, "rad")


def _rad_to_out(val, units, kind):
    if units == "sexigesimal":
        units = "hmsstr" if kind == "ra" else "dmsstr"
    return protractor.convert(val, "rad", units)


def ha_from_lst(lst, ra):
    """Hour angle from local sidereal time and RA (any consistent units)."""
    return lst - ra


def ha_from_mjdlon(mjd, lon, ra):
    """Hour angle (hours) from MJD, longitude (deg, West negative), RA (hours)."""
    from pypulsar_tpu.astro import clock

    return clock.MJD_lon_to_LST(mjd, lon) - ra


def equatorial_to_ecliptic(ra, decl, input="sexigesimal", output="deg", J2000=True):
    """(RA, decl) -> ecliptic (longitude, latitude)."""
    obliquity = OBLIQUITY_J2000 if J2000 else OBLIQUITY_B1950
    ra = _in_to_rad(ra, input, "ra")
    decl = _in_to_rad(decl, input, "dec")

    lon = np.arctan2(
        np.sin(ra) * np.cos(obliquity) + np.tan(decl) * np.sin(obliquity), np.cos(ra)
    )
    lat = np.arcsin(
        np.sin(decl) * np.cos(obliquity) - np.cos(decl) * np.sin(obliquity) * np.sin(ra)
    )
    lon = np.mod(lon, 2 * np.pi)
    lat = np.mod(lat, 2 * np.pi)
    return (_rad_to_out(lon, output, "dec"), _rad_to_out(lat, output, "dec"))


def ecliptic_to_equatorial(lon, lat, input="deg", output="sexigesimal", J2000=True):
    """Ecliptic (longitude, latitude) -> (RA, decl)."""
    obliquity = OBLIQUITY_J2000 if J2000 else OBLIQUITY_B1950
    lon = _in_to_rad(lon, input, "dec")
    lat = _in_to_rad(lat, input, "dec")

    ra = np.arctan2(
        np.sin(lon) * np.cos(obliquity) - np.tan(lat) * np.sin(obliquity), np.cos(lon)
    )
    decl = np.arcsin(
        np.sin(lat) * np.cos(obliquity) + np.cos(lat) * np.sin(obliquity) * np.sin(lon)
    )
    ra = np.mod(ra, 2 * np.pi)
    decl = np.mod(decl, 2 * np.pi)
    return (_rad_to_out(ra, output, "ra"), _rad_to_out(decl, output, "dec"))


def hadec_to_altaz(ha, decl, obslat, input="sexigesimal", output="deg"):
    """(hour angle, decl) + observer latitude (rad) -> (altitude, azimuth)."""
    ha = _in_to_rad(ha, input, "ra")
    decl = _in_to_rad(decl, input, "dec")

    alt = np.arcsin(
        np.sin(obslat) * np.sin(decl) + np.cos(obslat) * np.cos(decl) * np.cos(ha)
    )
    az = np.arccos(
        (np.sin(decl) - np.sin(obslat) * np.sin(alt)) / (np.cos(obslat) * np.cos(alt))
    )
    az = np.mod(az, 2 * np.pi)
    alt = np.mod(alt, 2 * np.pi)
    return (_rad_to_out(alt, output, "dec"), _rad_to_out(az, output, "dec"))


def altaz_to_hadec(alt, az, obslat, input="deg", output="sexigesimal"):
    """(altitude, azimuth) + observer latitude (rad) -> (hour angle, decl)."""
    alt = _in_to_rad(alt, input, "dec")
    az = _in_to_rad(az, input, "dec")

    ha = np.arctan2(
        np.sin(az), np.cos(az) * np.sin(obslat) + np.tan(alt) * np.cos(obslat)
    )
    decl = np.arcsin(
        np.sin(obslat) * np.sin(alt) - np.cos(obslat) * np.cos(alt) * np.cos(az)
    )
    ha = np.mod(ha, 2 * np.pi)
    decl = np.mod(decl, 2 * np.pi)
    return (_rad_to_out(ha, output, "ra"), _rad_to_out(decl, output, "dec"))


def equatorial_to_galactic(ra, decl, input="sexigesimal", output="deg", J2000=True):
    """(RA, decl) -> galactic (l, b). Input equinox J2000 (precessed to B1950
    internally) or B1950 directly."""
    ra = _in_to_rad(ra, input, "ra")
    decl = _in_to_rad(decl, input, "dec")
    if J2000:
        ra, decl = precess_J2000_to_B1950(ra, decl, input="rad", output="rad")

    x = np.arctan2(
        np.sin(GAL_POLE_RA_B1950 - ra),
        np.cos(GAL_POLE_RA_B1950 - ra) * np.sin(GAL_POLE_DECL_B1950)
        - np.tan(decl) * np.cos(GAL_POLE_DECL_B1950),
    )
    l = GAL_ORIGIN_L - x
    b = np.arcsin(
        np.sin(decl) * np.sin(GAL_POLE_DECL_B1950)
        + np.cos(decl) * np.cos(GAL_POLE_DECL_B1950) * np.cos(GAL_POLE_RA_B1950 - ra)
    )

    l = np.atleast_1d(np.mod(l, 2 * np.pi))
    b = np.atleast_1d(np.mod(b, 2 * np.pi))
    b[b > np.pi] -= 2 * np.pi

    l = np.asarray(_rad_to_out(l, output, "dec"))
    b = np.asarray(_rad_to_out(b, output, "dec"))
    return (l.squeeze(), b.squeeze())


def _precess(ra, decl, matrix, input, output):
    ra = _in_to_rad(ra, input, "ra")
    decl = _in_to_rad(decl, input, "dec")

    xyz = np.stack(
        [np.cos(ra) * np.cos(decl), np.sin(ra) * np.cos(decl), np.sin(decl)], axis=0
    )
    x2, y2, z2 = np.tensordot(matrix, xyz, axes=1)

    ra2 = np.mod(np.arctan2(y2, x2), 2 * np.pi)
    decl2 = np.mod(np.arcsin(np.clip(z2, -1.0, 1.0)), 2 * np.pi)
    return (_rad_to_out(ra2, output, "ra"), _rad_to_out(decl2, output, "dec"))


def precess_B1950_to_J2000(ra, decl, input="sexigesimal", output="sexigesimal"):
    """Precess B1950 equinox coords to J2000."""
    return _precess(ra, decl, _B1950_TO_J2000, input, output)


def precess_J2000_to_B1950(ra, decl, input="sexigesimal", output="sexigesimal"):
    """Precess J2000 equinox coords to B1950."""
    return _precess(ra, decl, _J2000_TO_B1950, input, output)


def angsep(ra1, dec1, ra2, dec2, input="sexigesimal", output="deg"):
    """Angular separation between two sky positions.

    ``input`` may be one units string for both coordinate pairs or a 2-tuple
    (units1, units2).
    """
    if isinstance(input, str):
        input1 = input2 = input
    else:
        input1, input2 = input
    ra1 = _in_to_rad(ra1, input1, "ra")
    dec1 = _in_to_rad(dec1, input1, "dec")
    ra2 = _in_to_rad(ra2, input2, "ra")
    dec2 = _in_to_rad(dec2, input2, "dec")

    cossep = np.sin(dec1) * np.sin(dec2) + np.cos(dec1) * np.cos(dec2) * np.cos(
        ra1 - ra2
    )
    sep = np.arccos(np.clip(cossep, -1.0, 1.0))
    return protractor.convert(sep, "rad", output)
