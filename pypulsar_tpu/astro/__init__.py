"""Astrometry / time utilities (host-side NumPy; no TPU need).

Behavioral parity targets (reference files):
- utils/astro/protractor.py — angle conversions
- utils/astro/calendar.py   — JD/MJD/date arithmetic
- utils/astro/clock.py      — sidereal time
- utils/astro/sextant.py    — coordinate transforms
- utils/coordconv.py        — compact RA/DEC string formats
- utils/telescopes.py       — telescope/TEMPO-site tables
"""

from pypulsar_tpu.astro import protractor, calendar, clock, sextant, coordconv
from pypulsar_tpu.astro import healpix, skytemp, estimate_snr
from pypulsar_tpu.astro.telescopes import (
    telescope_to_id,
    id_to_telescope,
    telescope_to_maxha,
)

__all__ = [
    "protractor",
    "calendar",
    "clock",
    "sextant",
    "coordconv",
    "healpix",
    "skytemp",
    "estimate_snr",
    "telescope_to_id",
    "id_to_telescope",
    "telescope_to_maxha",
]
