"""Compact RA/DEC string formats (parity: reference utils/coordconv.py).

HHMMSS.SSSS / DDMMSS.SSSS compact strings <-> radians/degrees/colon-separated
strings. The galactic conversion is slalib-free (goes through sextant's
precession-based transform; agrees with slalib to <0.1 arcsec-scale for
catalog work).
"""

import numpy as np

from pypulsar_tpu.astro import protractor, sextant


def sign_to_int(sign):
    """'+'/'-' -> +1/-1."""
    if sign == "+":
        return 1
    if sign == "-":
        return -1
    raise ValueError("sign is not '+' or '-' in function sign_to_int.")


def parse_decstr(decstr):
    """Parse declination string DDMMSS.SSSS -> (sign, d, m, s) strings."""
    decstr = str(decstr)
    decl = float(decstr)
    if decl == 0:
        return ("+", "00", "00", "00")
    sign = "+" if decl > 0 else "-"
    decl = str(abs(decl))
    if "." in decl:
        whole, frac = decl.split(".")
        frac = ".%s" % frac
    else:
        whole, frac = decl, ""
    padded = whole.zfill(6)
    return (sign, padded[0:2], padded[2:4], "%s%s" % (padded[4:6], frac))


def decstr_to_rad(decstr):
    """Declination string DDMMSS.SSSS -> radians."""
    sign, d, m, s = parse_decstr(str(decstr))
    return sign_to_int(sign) * protractor.dms_to_rad(float(d), float(m), float(s))


def decstr_to_deg(decstr):
    """Declination string DDMMSS.SSSS -> degrees."""
    return decstr_to_rad(decstr) * protractor.RADTODEG


def decstr_to_fmdecstr(decstr):
    """DDMMSS.SSSS -> +/-DD:MM:SS.SSSS."""
    return "%s%s:%s:%s" % parse_decstr(str(decstr))


def fmdecstr_to_decstr(fmdecstr):
    """+/-DD:MM:SS.SSSS -> DDMMSS.SSSS."""
    nocols = fmdecstr.replace(":", "")
    if nocols[0] in "+-":
        sign, nocols = nocols[0], nocols[1:]
    else:
        sign = ""
    value = float(nocols) if "." in nocols else int(nocols)
    return "%s%s" % (sign, value)


def parse_rastr(rastr):
    """Parse right ascension string HHMMSS.SSSS -> (h, m, s) strings."""
    rastr = str(rastr)
    if float(rastr) == 0:
        return ("00", "00", "00")
    if rastr[0] == "+":
        rastr = rastr[1:]
    if "." in rastr:
        whole, frac = rastr.split(".")
        frac = ".%s" % frac
    else:
        whole, frac = rastr, ""
    padded = whole.zfill(6)
    return (padded[0:2], padded[2:4], "%s%s" % (padded[4:6], frac))


def rastr_to_rad(rastr):
    """Right ascension string HHMMSS.SSSS -> radians."""
    h, m, s = parse_rastr(str(rastr))
    return protractor.hms_to_rad(float(h), float(m), float(s))


def rastr_to_deg(rastr):
    """Right ascension string HHMMSS.SSSS -> degrees."""
    return rastr_to_rad(rastr) * protractor.RADTODEG


def rastr_to_fmrastr(rastr):
    """HHMMSS.SSSS -> HH:MM:SS.SSSS."""
    return "%s:%s:%s" % parse_rastr(str(rastr))


def fmrastr_to_rastr(fmrastr):
    """HH:MM:SS.SSSS -> HHMMSS.SSSS."""
    nocols = fmrastr.replace(":", "")
    value = float(nocols) if "." in nocols else int(nocols)
    return "%s" % value


def eqdeg_to_galdeg(ra, decl):
    """J2000 (RA, decl) in degrees -> galactic (l, b) in degrees."""
    l, b = sextant.equatorial_to_galactic(ra, decl, input="deg", output="deg", J2000=True)
    return (np.asarray(l)[()], np.asarray(b)[()])
