"""Calendar / Julian-date arithmetic (parity: reference utils/astro/calendar.py).

Standard Meeus/Duffett-Smith algorithms, vectorized. Dates may be Gregorian
or Julian-calendar; ``day`` may be fractional.
"""

import datetime

import numpy as np

MONTH_NAMES = [
    "January", "February", "March", "April", "May", "June",
    "July", "August", "September", "October", "November", "December",
]


def JD_to_MJD(JD):
    """Julian Day to Modified Julian Day."""
    return np.asarray(JD) - 2400000.5


def MJD_to_JD(MJD):
    """Modified Julian Day to Julian Day."""
    return np.asarray(MJD) + 2400000.5


def date_to_JD(year, month, day, gregorian=True):
    """Calendar date (fractional day OK) to Julian Day (Meeus ch. 7)."""
    year = np.atleast_1d(year).astype(float)
    month = np.atleast_1d(month).astype(float)
    day = np.atleast_1d(day).astype(float)
    year, month, day = np.broadcast_arrays(year, month, day)
    year = year.copy()
    month = month.copy()

    shift = month <= 2
    year[shift] -= 1
    month[shift] += 12

    if gregorian:
        A = np.floor(year / 100.0)
        B = 2 - A + np.floor(A / 4.0)
    else:
        B = np.zeros_like(year)

    C = np.where(year < 0, np.floor(365.25 * year - 0.75), np.floor(365.25 * year))
    D = np.floor(30.6001 * (month + 1))
    JD = B + C + D + day + 1720994.5
    return JD.squeeze()


def date_to_MJD(*args, **kwargs):
    """Calendar date to Modified Julian Day."""
    return JD_to_MJD(date_to_JD(*args, **kwargs))


def MJDnow(gregorian=True):
    """Current UTC time as MJD."""
    utc = datetime.datetime.utcnow()
    dayfrac = (
        utc.day
        + (utc.hour + (utc.minute + (utc.second + utc.microsecond * 1e-6) / 60.0) / 60.0)
        / 24.0
    )
    return date_to_MJD(utc.year, utc.month, dayfrac, gregorian)


def julian_to_JD(year, month, day):
    return date_to_JD(year, month, day, gregorian=False)


def gregorian_to_JD(year, month, day):
    return date_to_JD(year, month, day, gregorian=True)


def gregorian_to_MJD(year, month, day):
    return JD_to_MJD(gregorian_to_JD(year, month, day))


def julian_to_MJD(year, month, day):
    return JD_to_MJD(julian_to_JD(year, month, day))


def JD_to_date(JD):
    """Julian Day to (year, month, fractional day) (Meeus ch. 7 inverse)."""
    JD = np.atleast_1d(JD).astype(float) + 0.5
    Z = np.floor(JD)
    F = JD - Z

    alpha = np.floor((Z - 1867216.25) / 36524.25)
    A = np.where(Z < 2299161, Z, Z + 1 + alpha - np.floor(alpha / 4.0))
    B = A + 1524
    C = np.floor((B - 122.1) / 365.25)
    D = np.floor(365.25 * C)
    E = np.floor((B - D) / 30.6001)

    day = B - D - np.floor(30.6001 * E) + F
    month = np.where(E < 14, E - 1, E - 13)
    year = np.where(month > 2, C - 4716, C - 4715)
    return (
        year.astype("int").squeeze(),
        month.astype("int").squeeze(),
        day.squeeze(),
    )


def MJD_to_date(MJD):
    """Modified Julian Day to (year, month, fractional day)."""
    return JD_to_date(MJD_to_JD(MJD))


def is_leap_year(year, gregorian=True):
    year = np.atleast_1d(year).astype(int)
    if gregorian:
        leap = ((year % 4) == 0) & (((year % 100) != 0) | ((year % 400) == 0))
    else:
        leap = (year % 4) == 0
    return leap.squeeze()


def is_gregorian_leap_year(year):
    return is_leap_year(year, gregorian=True)


def is_julian_leap_year(year):
    return is_leap_year(year, gregorian=False)


def first_of_year_JD(year):
    """JD of Jan 1.0 of ``year``."""
    return date_to_JD(year, 1, 1.0)


def first_of_year_MJD(year):
    return JD_to_MJD(first_of_year_JD(year))


def day_of_year(year, month, day, gregorian=True):
    """Day number within the year (Jan 1 = 1; fractional day OK)."""
    year = np.atleast_1d(year)
    month = np.atleast_1d(month).astype(int)
    day = np.atleast_1d(day)
    K = np.where(is_leap_year(np.atleast_1d(year), gregorian), 1, 2)
    N = np.floor(275.0 * month / 9.0) - K * np.floor((month + 9) / 12.0) + day - 30
    return N.squeeze()


def day_of_week(year, month, day):
    """0=Sunday .. 6=Saturday? Returns JD mod 7 (reference parity:
    0 corresponds to the weekday of JD=0 epoch + offset)."""
    JD = date_to_JD(year, month, np.floor(np.atleast_1d(day).astype(float))) + 1.5
    return np.mod(JD, 7).astype(int).squeeze()


def month_to_num(month):
    """Month name(s) (or unambiguous prefix) to number 1-12."""
    months = np.atleast_1d(month)
    nums = np.zeros(months.size, dtype=int)
    for i, m in enumerate(months):
        matches = [
            j + 1 for j, name in enumerate(MONTH_NAMES) if name.lower().startswith(str(m).lower())
        ]
        if len(matches) != 1:
            raise ValueError("Ambiguous or unknown month: %s" % m)
        nums[i] = matches[0]
    return nums.squeeze()[()] if nums.size == 1 else nums


def num_to_month(month):
    """Month number(s) 1-12 to name(s)."""
    months = np.atleast_1d(month)
    strings = [MONTH_NAMES[int(m) - 1] for m in months]
    return strings[0] if len(strings) == 1 else strings


def date_to_string(year, month, day):
    """Format date(s) as 'Month DD, YYYY'."""
    year = np.atleast_1d(year)
    month = np.atleast_1d(month)
    day = np.atleast_1d(day)
    year, month, day = np.broadcast_arrays(year, month, day)
    out = [
        "%s %d, %d" % (MONTH_NAMES[int(m) - 1], int(d), int(y))
        for y, m, d in zip(year, month, day)
    ]
    return out[0] if len(out) == 1 else out


def interval_in_days(year1, month1, day1, year2, month2, day2, gregorian=True):
    """Days between two calendar dates (date2 - date1)."""
    diff = date_to_JD(year2, month2, day2, gregorian) - date_to_JD(
        year1, month1, day1, gregorian
    )
    return np.asarray(diff).squeeze()


def fraction_of_year(year, month, day, gregorian=True):
    """Elapsed fraction of the year at the given date."""
    year = np.atleast_1d(year)
    ndays = np.where(is_leap_year(year, gregorian), 366.0, 365.0)
    frac = (day_of_year(year, month, day, gregorian) - 1.0) / ndays
    return np.asarray(frac).squeeze()


def MJD_to_year(MJD):
    """MJD to fractional year."""
    year, month, day = MJD_to_date(MJD)
    return year + fraction_of_year(year, month, day)


def year_to_MJD(year):
    """Fractional year to MJD."""
    year = np.atleast_1d(np.asarray(year, dtype=float))
    whole = np.floor(year).astype(int)
    frac = year - whole
    ndays = np.where(is_leap_year(whole), 366.0, 365.0)
    mjd = first_of_year_MJD(whole) + frac * ndays
    return np.asarray(mjd).squeeze()


def MJD_to_datestring(MJD):
    """MJD to 'Month DD, YYYY'."""
    return date_to_string(*MJD_to_date(MJD))


def datetime_to_MJD(dt, gregorian=True):
    """datetime.datetime (naive=UTC or tz-aware) to MJD."""
    if dt.tzinfo is not None:
        dt = dt.astimezone(datetime.timezone.utc).replace(tzinfo=None)
    dayfrac = (
        dt.day
        + (dt.hour + (dt.minute + (dt.second + dt.microsecond * 1e-6) / 60.0) / 60.0) / 24.0
    )
    return date_to_MJD(dt.year, dt.month, dayfrac, gregorian)


def MJD_to_datetime(mjd):
    """MJD to naive UTC datetime.datetime."""
    year, month, day = MJD_to_date(mjd)
    whole = int(np.floor(day))
    frac = float(day) - whole
    hours = frac * 24.0
    h = int(hours)
    mins = (hours - h) * 60.0
    m = int(mins)
    secs = (mins - m) * 60.0
    s = int(secs)
    micro = int(round((secs - s) * 1e6))
    if micro >= 1000000:
        micro -= 1000000
        s += 1
    return datetime.datetime(int(year), int(month), whole, h, m, s, micro)
