"""Sidereal time (parity: reference utils/astro/clock.py, minus debug prints).

Duffett-Smith "Practical Astronomy with your Calculator" 3rd Ed., section 12.
"""

import numpy as np

from pypulsar_tpu.astro import calendar


def JD_to_GST(JD):
    """Julian Day to Greenwich mean sidereal time in hours."""
    JD = np.array(JD, dtype=float)
    days = (JD - 0.5) % 1
    hours = days * 24

    JD0 = JD - days
    T = (JD0 - 2451545.0) / 36525.0
    T0 = (6.697374558 + 2400.051336 * T + 0.000025862 * T**2) % 24
    UT = hours * 1.002737909
    return (UT + T0) % 24


def MJD_to_GST(MJD):
    """Modified Julian Day to Greenwich mean sidereal time in hours."""
    return JD_to_GST(calendar.MJD_to_JD(MJD))


def MJD_lon_to_LST(MJD, lon):
    """Local sidereal time (hours) at ``MJD`` for longitude ``lon`` (degrees;
    West negative, East positive)."""
    GST = MJD_to_GST(MJD)
    return (GST + lon / 15.0) % 24.0


def JD_to_mstUT_deg(JD):
    """Julian Day to mean sidereal time (UT) in degrees (IAU 1982 expansion)."""
    JD = np.array(JD, dtype=float)
    T = (JD - 2451545.0) / 36525.0
    return (
        280.46061837
        + 360.98564736629 * (JD - 2451545.0)
        + 0.000387933 * T**2
        - T**3 / 38710000.0
    )


def MJD_to_mstUT_deg(MJD):
    """Modified Julian Day to mean sidereal time (UT) in degrees."""
    return JD_to_mstUT_deg(calendar.MJD_to_JD(MJD))
