"""Angle conversion suite (parity: reference utils/astro/protractor.py).

All numeric conversions are vectorized NumPy; sexagesimal string parsing
accepts scalars or sequences. The generic ``convert(values, in, out)``
dispatches through radians exactly like the reference (:168-197).
"""

import re
import warnings

import numpy as np

DEGTORAD = np.pi / 180.0
RADTODEG = 180.0 / np.pi
HOURTORAD = np.pi / 12.0
RADTOHOUR = 12.0 / np.pi

hms_re = re.compile(
    r"^(?P<sign>[-+])?(?P<hour>\d{2}):(?P<min>\d{2})" r"(?::(?P<sec>\d{2}(?:.\d+)?))?$"
)
dms_re = re.compile(
    r"^(?P<sign>[-+])?(?P<deg>\d{2}):(?P<min>\d{2})" r"(?::(?P<sec>\d{2}(?:.\d+)?))?$"
)


def _sexstr_to_float(strings, regex, what):
    strings = np.atleast_1d(strings)
    out = np.zeros(strings.size)
    for i, s in enumerate(strings):
        m = regex.match(s)
        if m is None:
            warnings.warn("Input is not a valid sexigesimal string: %s" % s)
            out[i] = np.nan
            continue
        d = m.groupdict(0)
        sign = -1.0 if d["sign"] == "-" else 1.0
        out[i] = sign * (float(d[what]) + float(d["min"]) / 60.0 + float(d["sec"]) / 3600.0)
    return out


def hmsstr_to_rad(hmsstr):
    """Convert HH:MM:SS.SS sexigesimal string(s) to radians."""
    return hour_to_rad(_sexstr_to_float(hmsstr, hms_re, "hour"))


def dmsstr_to_rad(dmsstr):
    """Convert DD:MM:SS.SS sexigesimal string(s) to radians."""
    return deg_to_rad(_sexstr_to_float(dmsstr, dms_re, "deg"))


def _to_sexstr(rads, to_units):
    signs = np.atleast_1d(np.sign(rads))
    vals = np.atleast_1d(to_units(np.abs(rads)))
    strs = []
    for sign, val in zip(signs, vals):
        val = val + 1e-12  # guard against machine-precision 59.9999->60 flips
        whole = int(val)
        mins = (val - whole) * 60.0
        m = int(mins)
        s = (mins - m) * 60.0
        signstr = "-" if sign == -1 else ""
        if s >= 9.9995:
            strs.append("%s%.2d:%.2d:%.4f" % (signstr, whole, m, s))
        else:
            strs.append("%s%.2d:%.2d:0%.4f" % (signstr, whole, m, s))
    return strs


def rad_to_hmsstr(rads):
    """Convert radians to HH:MM:SS.SS sexigesimal string(s)."""
    return _to_sexstr(rads, rad_to_hour)


def rad_to_dmsstr(rads):
    """Convert radians to DD:MM:SS.SS sexigesimal string(s)."""
    return _to_sexstr(rads, rad_to_deg)


def hour_to_rad(hours):
    return np.array(hours) * HOURTORAD


def rad_to_hour(rads):
    return np.array(rads) * RADTOHOUR


def deg_to_rad(degs):
    return np.array(degs) * DEGTORAD


def rad_to_deg(rads):
    return np.array(rads) * RADTODEG


def rad_to_rad(rads):
    return rads


def hms_to_rad(hour, minute, sec):
    """(h, m, s) numeric triple to radians (psr_utils.hms_to_rad parity)."""
    sign = np.where(np.array(hour) < 0, -1.0, 1.0)
    return (
        sign
        * HOURTORAD
        * (np.abs(np.array(hour)) + np.array(minute) / 60.0 + np.array(sec) / 3600.0)
    )


def dms_to_rad(deg, minute, sec):
    """(d, m, s) numeric triple to radians (psr_utils.dms_to_rad parity)."""
    deg = np.array(deg)
    sign = np.where(deg < 0, -1.0, np.where((deg == 0) & (np.array(minute) < 0), -1.0, 1.0))
    return (
        sign
        * DEGTORAD
        * (np.abs(deg) + np.abs(np.array(minute)) / 60.0 + np.abs(np.array(sec)) / 3600.0)
    )


def convert(values, input, output):
    """Convert ``values`` between any two of hmsstr/dmsstr/hour/deg/rad,
    dispatching through radians."""
    return getfunction("rad_to_%s" % output)(getfunction("%s_to_rad" % input)(values))


def getfunction(reqfunc_name):
    func = globals().get(reqfunc_name)
    if not callable(func):
        raise ValueError("Requested conversion (%s) doesn't exist!" % reqfunc_name)
    return func
