"""Radiometer-equation SNR estimation for known pulsars.

Parity target: reference utils/estimate_snr.py (SnrEstimator :20-108,
airy_pattern :111-123, change_freq :126-143).  The SNR model:

    SNR = S * G * Airy(sep) * sqrt(npol * t * BW) / (Tsys + Tsky + TCMB)
          * sqrt((P - w) / w)

with gain/systemp/fwhm optionally callables of (za, az) — the Arecibo
zenith-angle gain curves in ``zaaz`` plug in here.
"""

from __future__ import annotations

from typing import Callable, Optional, Union

import numpy as np
from scipy import special

from pypulsar_tpu.astro import skytemp

TCMB = 2.73  # K

ScalarOrFunc = Union[float, Callable[..., float]]


def _as_func(v: ScalarOrFunc) -> Callable[..., float]:
    return v if callable(v) else (lambda za=0, az=0: v)


def airy_pattern(fwhm, x) -> np.ndarray:
    """Airy beam power pattern normalized to Airy(0)=1; ``fwhm`` and ``x``
    in the same angular units (reference :111-123; half-max at 1.61633)."""
    x = np.atleast_1d(np.asarray(x, dtype=np.float64))
    scaled_x = x / fwhm * (2.0 * 1.61633)
    with np.errstate(divide="ignore", invalid="ignore"):
        airy = np.atleast_1d((2 * special.j1(scaled_x) / scaled_x) ** 2)
    airy[x == 0] = 1.0
    return airy


def change_freq(S, error, oldfreq, newfreq, index):
    """Power-law flux scaling to a new frequency (reference :126-143)."""
    k = (float(newfreq) / float(oldfreq)) ** index
    newS = S * k
    newerror = error * k if error is not None else None
    return newS, newerror


class SnrEstimator:
    """Estimate the radiometer SNR of a known pulsar in a given setup.

    freq/bw in MHz, gain in K/Jy, systemp in K, fwhm in arcmin;
    gain/systemp/fwhm may be callables of (za, az) in degrees.
    """

    def __init__(self, freq, bw, numpol, gain: ScalarOrFunc,
                 systemp: ScalarOrFunc, fwhm: ScalarOrFunc):
        self.freq = freq
        self.bw = bw
        self.numpol = numpol
        self.gain = _as_func(gain)
        self.systemp = _as_func(systemp)
        self.fwhm = _as_func(fwhm)
        self.beam_profile = airy_pattern

    def estimate_snr(self, za, az, Smean, Sfreq, time, angsep, period,
                     w50=None, Serror=None, l=None, b=None, spindx=-1.8,
                     mapfn: Optional[str] = None):
        """SNR and its error (reference :61-108).

        za/az deg; Smean mJy at Sfreq MHz; time s; angsep arcmin;
        period s; w50 s (default 5% of period); (l, b) galactic deg for
        the Tsky term (0 K when omitted)."""
        if w50 is None:
            w50 = 0.05 * period
        if Serror is None:
            Serror = 0.0

        if self.freq != Sfreq:
            Smean, Serror = change_freq(Smean, Serror, oldfreq=Sfreq,
                                        newfreq=self.freq, index=spindx)

        if l is not None and b is not None:
            Tsky = skytemp.get_skytemp(l, b, freq=self.freq, mapfn=mapfn)
        else:
            Tsky = 0.0
        temp = self.systemp(za, az) + Tsky + TCMB

        k = (self.gain(za, az) * self.beam_profile(self.fwhm(za, az), angsep)
             * np.sqrt(self.numpol * time * self.bw) / temp
             * np.sqrt((period - w50) / w50))

        Smean = np.atleast_1d(Smean)
        Serror = np.atleast_1d(Serror)
        snr = Smean * k
        snrerror = np.where(Serror == 0, np.nan, Serror * k)
        return snr, snrerror
