"""Minimal HEALPix (RING scheme) pixelization + bilinear interpolation.

Replaces the external ``healpy`` dependency of the reference's sky
temperature lookup (reference utils/skytemp.py:20,71 — only
``get_interp_val`` is used).  Implements the standard RING-scheme
geometry (Gorski et al. 2005) in vectorized NumPy:

- ring layout: north cap rings i=1..nside-1 (4i pixels), equatorial
  rings i=nside..3*nside (4*nside pixels, alternating half-pixel phase),
  south cap mirrored;
- ``ang2pix`` nearest-pixel lookup;
- ``get_interp_val``: healpy-style bilinear interpolation between the
  two rings bracketing theta and the two pixels bracketing phi on each.
"""

from __future__ import annotations

import numpy as np

TWOPI = 2.0 * np.pi


def npix(nside: int) -> int:
    return 12 * nside * nside


def nside_from_npix(n: int) -> int:
    nside = int(round(np.sqrt(n / 12.0)))
    if 12 * nside * nside != n:
        raise ValueError(f"{n} is not a valid HEALPix map size")
    return nside


def _ring_info(nside: int, i: np.ndarray):
    """Per-ring geometry for ring index i in [1, 4*nside-1]: returns
    (startpix, ringpix, z, phase) where pixel centers on the ring sit at
    phi_j = (j + phase) * 2*pi/ringpix."""
    i = np.asarray(i, dtype=np.int64)
    ncap = 2 * nside * (nside - 1)
    north = i < nside
    south = i > 3 * nside
    eq = ~(north | south)

    startpix = np.empty_like(i)
    ringpix = np.empty_like(i)
    z = np.empty(i.shape, dtype=np.float64)
    phase = np.empty(i.shape, dtype=np.float64)

    # north polar cap
    ic = i[north]
    startpix[north] = 2 * ic * (ic - 1)
    ringpix[north] = 4 * ic
    z[north] = 1.0 - ic.astype(np.float64) ** 2 / (3.0 * nside**2)
    phase[north] = 0.5

    # equatorial belt
    ie = i[eq]
    startpix[eq] = ncap + (ie - nside) * 4 * nside
    ringpix[eq] = 4 * nside
    z[eq] = 4.0 / 3.0 - 2.0 * ie.astype(np.float64) / (3.0 * nside)
    phase[eq] = 0.5 * ((ie - nside + 1) % 2)

    # south polar cap
    isc = 4 * nside - i[south]
    startpix[south] = npix(nside) - 2 * isc * (isc + 1)
    ringpix[south] = 4 * isc
    z[south] = -(1.0 - isc.astype(np.float64) ** 2 / (3.0 * nside**2))
    phase[south] = 0.5
    return startpix, ringpix, z, phase


def _bracketing_rings(nside: int, z: np.ndarray):
    """Ring indices (i1, i2) above/below colatitude-cosine z, clipped to
    the valid range (at the caps both collapse to the extreme ring)."""
    z = np.clip(np.asarray(z, dtype=np.float64), -1.0, 1.0)
    # invert the z(i) relations
    i_eq = (4.0 / 3.0 - z) * (3.0 * nside) / 2.0
    with np.errstate(invalid="ignore"):
        i_north = nside * np.sqrt(np.maximum(3.0 * (1.0 - z), 0.0))
        i_south = 4 * nside - nside * np.sqrt(np.maximum(3.0 * (1.0 + z), 0.0))
    i_real = np.where(
        z > 2.0 / 3.0, i_north, np.where(z < -2.0 / 3.0, i_south, i_eq)
    )
    i1 = np.floor(i_real).astype(np.int64)
    i2 = i1 + 1
    i1 = np.clip(i1, 1, 4 * nside - 1)
    i2 = np.clip(i2, 1, 4 * nside - 1)
    return i1, i2, i_real


def _ring_interp(nside: int, ring: np.ndarray, phi: np.ndarray):
    """On each given ring, the two pixel indices bracketing phi and the
    weight of the second one."""
    startpix, ringpix, _, phase = _ring_info(nside, ring)
    dphi = TWOPI / ringpix
    x = phi / dphi - phase
    j1 = np.floor(x).astype(np.int64)
    w2 = x - j1
    j2 = (j1 + 1) % ringpix
    j1 = j1 % ringpix
    return startpix + j1, startpix + j2, w2


def get_interp_val(m: np.ndarray, theta, phi) -> np.ndarray:
    """Bilinear interpolation of map ``m`` at (theta, phi) in radians
    (healpy.get_interp_val semantics for RING-ordered maps)."""
    m = np.asarray(m)
    nside = nside_from_npix(m.shape[-1])
    theta = np.atleast_1d(np.asarray(theta, dtype=np.float64))
    phi = np.mod(np.atleast_1d(np.asarray(phi, dtype=np.float64)), TWOPI)
    shape = np.broadcast(theta, phi).shape
    theta, phi = np.broadcast_arrays(theta, phi)
    z = np.cos(theta)

    i1, i2, i_real = _bracketing_rings(nside, z)
    _, _, z1, _ = _ring_info(nside, i1)
    _, _, z2, _ = _ring_info(nside, i2)

    pa1, pa2, wa = _ring_interp(nside, i1, phi)
    pb1, pb2, wb = _ring_interp(nside, i2, phi)

    with np.errstate(divide="ignore", invalid="ignore"):
        wz = np.where(i1 == i2, 0.0, (z1 - z) / np.where(z1 == z2, 1.0, z1 - z2))
    wz = np.clip(wz, 0.0, 1.0)

    va = m[..., pa1] * (1.0 - wa) + m[..., pa2] * wa
    vb = m[..., pb1] * (1.0 - wb) + m[..., pb2] * wb
    out = va * (1.0 - wz) + vb * wz
    return out.reshape(shape) if shape else out


def ang2pix(nside: int, theta, phi) -> np.ndarray:
    """Nearest RING-scheme pixel for (theta, phi) in radians."""
    theta = np.atleast_1d(np.asarray(theta, dtype=np.float64))
    phi = np.mod(np.atleast_1d(np.asarray(phi, dtype=np.float64)), TWOPI)
    z = np.cos(theta)
    i1, i2, i_real = _bracketing_rings(nside, z)
    # nearer ring of the two
    _, _, z1, _ = _ring_info(nside, i1)
    _, _, z2, _ = _ring_info(nside, i2)
    use2 = np.abs(z - z2) < np.abs(z - z1)
    ring = np.where(use2, i2, i1)
    startpix, ringpix, _, phase = _ring_info(nside, ring)
    j = np.round(phi / (TWOPI / ringpix) - phase).astype(np.int64) % ringpix
    return startpix + j


def pix2ang(nside: int, ipix) -> tuple:
    """RING pixel index -> (theta, phi) of the pixel center."""
    ipix = np.atleast_1d(np.asarray(ipix, dtype=np.int64))
    ncap = 2 * nside * (nside - 1)
    n = npix(nside)
    ring = np.empty_like(ipix)
    north = ipix < ncap
    south = ipix >= n - ncap
    eq = ~(north | south)
    # north cap: ipix = 2i(i-1)+j  =>  i = ceil of quadratic root
    ring[north] = (
        np.floor(0.5 * (1 + np.sqrt(1 + 2 * ipix[north]))).astype(np.int64)
    )
    # fix rounding at ring boundaries
    r = ring[north]
    r = np.where(2 * r * (r - 1) > ipix[north], r - 1, r)
    r = np.where(2 * (r + 1) * r <= ipix[north], r + 1, r)
    ring[north] = r
    ring[eq] = nside + (ipix[eq] - ncap) // (4 * nside)
    ips = n - 1 - ipix[south]
    rs = np.floor(0.5 * (1 + np.sqrt(1 + 2 * ips))).astype(np.int64)
    rs = np.where(2 * rs * (rs - 1) > ips, rs - 1, rs)
    rs = np.where(2 * (rs + 1) * rs <= ips, rs + 1, rs)
    ring[south] = 4 * nside - rs
    startpix, ringpix, z, phase = _ring_info(nside, ring)
    theta = np.arccos(np.clip(z, -1, 1))
    phi = (ipix - startpix + phase) * TWOPI / ringpix
    return theta, phi
