"""Telescope tables (parity: reference utils/telescopes.py).

Name <-> TEMPO observatory code and max-hour-angle lookups.
"""

telescope_to_id = {
    "GBT": "1",
    "Arecibo": "3",
    "VLA": "6",
    "Parkes": "7",
    "Jodrell": "8",
    "GB43m": "a",
    "GB 140FT": "a",
    "Nancay": "f",
    "Effelsberg": "g",
    "WSRT": "i",
    "GMRT": "r",
    "Geocenter": "0",
    "Barycenter": "@",
}

id_to_telescope = {
    "1": "GBT",
    "3": "Arecibo",
    "6": "VLA",
    "7": "Parkes",
    "8": "Jodrell",
    "a": "GB 140FT",
    "f": "Nancay",
    "g": "Effelsberg",
    "i": "WSRT",
    "r": "GMRT",
    "0": "Geocenter",
    "@": "Barycenter",
}

telescope_to_maxha = {
    "GBT": 12,
    "Arecibo": 3,
    "VLA": 6,
    "Parkes": 12,
    "Jodrell": 12,
    "GB43m": 12,
    "GB 140FT": 12,
    "Nancay": 4,
    "Effelsberg": 12,
    "WSRT": 12,
    "GMRT": 12,
    "Geocenter": 12,
    "Barycenter": 12,
}
