"""Sky temperature from the Haslam 408 MHz all-sky map.

Parity target: reference utils/skytemp.py (get_skytemp :55-78,
change_obsfreq :115-119 — honoring the §2.6 note that the reference
*ignores* its ``index`` argument; we use it).  healpy is replaced by our
own RING interpolation (pypulsar_tpu.astro.healpix) and the map is read
through our FITS codec.

The Haslam FITS blob is absent from the reference snapshot
(.MISSING_LARGE_BLOBS), so the map path is configurable: pass ``mapfn``,
set $PYPULSAR_TPU_HASLAM, or drop the file at lib/lambda_haslam408_dsds.fits

Fetch recipe (the public NASA LAMBDA archive hosts the destriped/
desourced Haslam 408 MHz map, ~50 MB HEALPix FITS)::

    curl -L -o lib/lambda_haslam408_dsds.fits \\
      https://lambda.gsfc.nasa.gov/data/foregrounds/haslam/lambda_haslam408_dsds.fits
    # or: export PYPULSAR_TPU_HASLAM=/path/to/lambda_haslam408_dsds.fits

tests/test_snr_stack.py writes a small synthetic map with the same
layout, so the suite never needs the download.
under the package root.  ``write_healpix_map`` lets tests (and users with
their own surveys) supply maps.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from pypulsar_tpu.astro import healpix
from pypulsar_tpu.tune import knobs

HASLAM_FREQ = 408.0  # MHz
SYNCHROTRON_INDEX = -2.7
DEGTORAD = np.pi / 180.0

def _default_paths():
    # env var read at call time, not import time
    return (
        knobs.env_str("PYPULSAR_TPU_HASLAM") or "",
        os.path.join(os.path.dirname(os.path.dirname(__file__)), "lib",
                     "lambda_haslam408_dsds.fits"),
    )

_MAP_CACHE = {}


def read_map(mapfn: Optional[str] = None) -> np.ndarray:
    """Load a HEALPix map from a FITS BINTABLE (first column, rows
    flattened in RING order — the LAMBDA file layout)."""
    if mapfn is None:
        for cand in _default_paths():
            if cand and os.path.isfile(cand):
                mapfn = cand
                break
        else:
            raise FileNotFoundError(
                "Haslam map not found. Set $PYPULSAR_TPU_HASLAM or pass "
                "mapfn= (the LAMBDA lambda_haslam408_dsds.fits file)."
            )
    if mapfn in _MAP_CACHE:
        return _MAP_CACHE[mapfn]
    try:
        from astropy.io import fits as pyfits
    except ImportError:
        from pypulsar_tpu.io import fitsio as pyfits
    with pyfits.open(mapfn) as hdus:
        table = None
        for hdu in hdus:
            if getattr(hdu, "columns", None):
                table = hdu
                break
        if table is None:
            raise ValueError(f"No binary table in {mapfn}")
        col = table.columns.names[0]
        data = np.asarray(table.data.field(col), dtype=np.float64).ravel()
    healpix.nside_from_npix(data.size)  # validates
    _MAP_CACHE[mapfn] = data
    return data


def write_healpix_map(mapfn: str, m: np.ndarray, colname: str = "TEMPERATURE",
                      rowlen: int = 1024) -> str:
    """Write a RING-ordered map as a FITS BINTABLE (LAMBDA-style layout)."""
    try:
        from astropy.io import fits as pyfits
    except ImportError:
        from pypulsar_tpu.io import fitsio as pyfits
    m = np.asarray(m, dtype=np.float32)
    if m.size % rowlen:
        rowlen = m.size
    col = pyfits.Column(name=colname, format=f"{rowlen}E",
                        array=m.reshape(-1, rowlen))
    hdu = pyfits.BinTableHDU.from_columns(pyfits.ColDefs([col]),
                                          name="XTENSION")
    hdu.header["PIXTYPE"] = "HEALPIX"
    hdu.header["ORDERING"] = "RING"
    hdu.header["NSIDE"] = healpix.nside_from_npix(m.size)
    pyfits.HDUList([pyfits.PrimaryHDU(), hdu]).writeto(mapfn, overwrite=True)
    return mapfn


def change_obsfreq(temp, oldfreq, newfreq, index=SYNCHROTRON_INDEX):
    """Scale brightness temperature by a synchrotron power law (reference
    :115-119; unlike the reference, ``index`` is honored)."""
    return temp * (newfreq / oldfreq) ** index


def approx_skytemp_408(gal_long, gal_lat):
    """Analytic approximation of the 408 MHz sky temperature (K): an
    isotropic ~25 K floor plus a galactic-plane/centre component falling
    off in longitude and latitude.  A coarse stand-in (tens of percent on
    the plane) for when the Haslam map file is unavailable."""
    l = np.mod(np.asarray(gal_long, dtype=np.float64) + 180.0, 360.0) - 180.0
    b = np.asarray(gal_lat, dtype=np.float64)
    return 25.0 + 275.0 / ((1.0 + (l / 42.0) ** 2) * (1.0 + (b / 3.0) ** 2))


def get_skytemp(gal_long, gal_lat, freq=HASLAM_FREQ,
                index=SYNCHROTRON_INDEX, mapfn: Optional[str] = None):
    """Sky temperature (K) at galactic (l, b) degrees, scaled to ``freq``
    MHz (reference :55-78).  Falls back to :func:`approx_skytemp_408`
    (with a warning) only when NO map was configured anywhere; an
    explicitly requested ``mapfn`` or $PYPULSAR_TPU_HASLAM path that is
    missing still raises, so a typo cannot silently degrade fluxes."""
    # configured = caller passed a path, the env var is SET (even if its
    # target is missing — a typo should raise, not degrade), or the
    # bundled default file exists
    envpath, libpath = _default_paths()
    configured = bool(mapfn) or bool(envpath) or os.path.isfile(libpath)
    if not configured:
        import warnings
        warnings.warn(
            "Haslam map unavailable; using the analytic plane-model "
            "approximation for the sky temperature.")
        temp_408 = approx_skytemp_408(gal_long, gal_lat)
        return change_obsfreq(temp_408, HASLAM_FREQ, freq, index)
    m = read_map(mapfn)
    theta = (90.0 - np.asarray(gal_lat, dtype=np.float64)) * DEGTORAD
    phi = np.asarray(gal_long, dtype=np.float64) * DEGTORAD
    temp_408 = healpix.get_interp_val(m, theta, phi)
    return change_obsfreq(temp_408, HASLAM_FREQ, freq, index)
