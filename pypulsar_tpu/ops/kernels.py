"""Pure-JAX kernels for the pulsar data plane.

Each kernel is a pure function on ``data[nchan, nspec]`` arrays mirroring the
behavior of a reference Spectra method (reference formats/spectra.py) or
preprocessing script, redesigned for XLA:

- per-channel variable shifts are index-gathers with static shapes (instead of
  the reference's Python loop of psr_utils.rotate at formats/spectra.py:76-94),
  so they vmap over DM trials and shard over a device mesh;
- integer bin delays may be passed in precomputed (host f64, exactly matching
  the reference's NumPy delay math) or computed on device from a traced DM;
- shape-changing ops (trim / downsample) take static Python ints.

NumPy golden twins live in ``pypulsar_tpu.ops.numpy_ref``; parity is enforced
in tests/test_kernels.py.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from pypulsar_tpu.core.psrmath import DM_CONST_INV
from pypulsar_tpu.tune import knobs


def delay_from_DM(dm, freqs):
    """Dispersion delay (s) at freqs (MHz). Device version of
    core.psrmath.delay_from_DM; 0 for non-positive frequencies."""
    freqs = jnp.asarray(freqs)
    return jnp.where(freqs > 0.0, dm / (DM_CONST_INV * freqs * freqs), 0.0)


def bin_delays(dm, freqs, dt, ref_freq=None):
    """Integer relative bin delays for dedispersion at ``dm`` (traced OK).

    Matches reference formats/spectra.py:247-250: delays relative to the
    highest frequency, rounded half-even (np.round semantics).
    """
    if ref_freq is None:
        ref_freq = jnp.max(freqs)
    rel = delay_from_DM(dm, freqs) - delay_from_DM(dm, ref_freq)
    return jnp.round(rel / dt).astype(jnp.int32)


def rotate_rows(data, bins):
    """Left-rotate each row of ``data[C, T]`` by ``bins[C]`` places (circular).

    Gather formulation of the reference's per-channel psr_utils.rotate loop
    (formats/spectra.py:76-80); works under vmap/jit with traced bins.
    """
    T = data.shape[-1]
    idx = (jnp.arange(T, dtype=jnp.int32)[None, :] + bins[:, None].astype(jnp.int32)) % T
    return jnp.take_along_axis(data, idx, axis=-1)


def shift_channels(data, bins, padval=0, backend="auto", n_fft=None):
    """Shift each channel left by bins[c]; pad vacated cells.

    padval: numeric, 'mean', 'median' (of the rotated channel — the reference
    computes pad stats after rotation, formats/spectra.py:81-94; a circular
    rotation permutes the row, so these equal the stats of the ORIGINAL
    row), or 'rotate' (pure circular shift).

    backend: 'gather' (take_along_axis; bit-exact reference formulation),
    'fourier' (pad to a power of two, integer phase multiply, irfft —
    values agree to FFT f32 rounding), or 'auto': fourier on TPU, where
    the generic row gather measures only ~70M elem/s (~670 ms for one
    [256, 156k] dedispersion) while the FFT path runs at HBM speed
    (BENCHNOTES round 5); gather elsewhere. 'rotate' padval always takes
    the gather path (the FFT formulation is a LINEAR shift — circular
    wrap-around of real data has period T, which is generally not a
    power of two and would lower to a dense DFT matmul on this
    platform).

    n_fft: static power-of-two FFT length for the fourier path. Callers
    with host-known bins can pass ``fourier_chunk_len(T + max|bins|)``
    (Spectra does) to halve the default 2T padding; must satisfy
    ``n_fft - T >= max|bins|`` or the wrap region overlaps real data."""
    if backend == "auto":
        backend = _resolve_shift_backend(padval, jnp.asarray(data).dtype)
    if backend == "fourier" and padval != "rotate":
        return _shift_channels_fourier(data, bins, padval, n_fft)
    return _shift_channels_gather(data, bins, padval)


def _resolve_shift_backend(padval, dtype) -> str:
    """'auto' policy, resolved at CALL time (PYPULSAR_TPU_SHIFT_BACKEND
    env override; else fourier on TPU for float data with a fillable
    padval, gather everywhere else). Callers that jit around
    shift_channels pass the resolved value as a static arg so the env
    override lands in their jit key instead of being frozen into the
    first-compiled executable."""
    import os

    return knobs.env_str("PYPULSAR_TPU_SHIFT_BACKEND") or (
        "fourier" if padval != "rotate"
        and jnp.issubdtype(dtype, jnp.floating)
        and jax.default_backend() == "tpu" else "gather")


def _vacated_fill(shifted, stats_src, bins, padval):
    """Overwrite the cells a left-shift by ``bins`` vacated with the pad
    value. 'mean'/'median' stats come from ``stats_src`` — the gather
    path passes the rotated row, the fourier path the original row; a
    circular rotation permutes the row so the two are identical."""
    if padval == "mean":
        pad = jnp.mean(stats_src, axis=-1, keepdims=True)
    elif padval == "median":
        pad = jnp.median(stats_src, axis=-1, keepdims=True)
    else:
        pad = jnp.full((shifted.shape[0], 1), padval, dtype=shifted.dtype)
    T = shifted.shape[-1]
    t = jnp.arange(T, dtype=jnp.int32)[None, :]
    b = bins[:, None].astype(jnp.int32)
    vacated = jnp.where(b > 0, t >= T - b, t < -b)
    return jnp.where(vacated, pad.astype(shifted.dtype), shifted)


@partial(jax.jit, static_argnames=("padval",))
def _shift_channels_gather(data, bins, padval=0):
    shifted = rotate_rows(data, bins)
    if padval == "rotate":
        return shifted
    return _vacated_fill(shifted, shifted, bins, padval)


@partial(jax.jit, static_argnames=("padval", "n_fft"))
def _shift_channels_fourier(data, bins, padval=0, n_fft=None):
    """Linear per-channel shift as a Fourier phase multiply.

    Rows are zero-padded to ``n = 2^ceil(log2(2T))`` and rotated by the
    exact integer phase ``W^(k*s)`` (index mod n via int32 wraparound —
    ops/fourier_dedisperse._phase); with ``|s| <= n - T`` the wrap region
    is all zeros, so ``out[:T]`` is the linear shift and the vacated-fill
    logic is identical to the gather path. Rows with ``|s| >= T`` are
    fully vacated and end up all-padval either way. Kept values carry FFT
    f32 rounding (~1e-6 relative; inside the documented 2e-6 SNR parity
    contract at detection level)."""
    from pypulsar_tpu.ops.fourier_dedisperse import _phase, fourier_chunk_len

    C, T = data.shape
    n = n_fft if n_fft is not None else fourier_chunk_len(2 * T)
    F = n // 2 + 1
    k = jnp.arange(F, dtype=jnp.int32)
    X = jnp.fft.rfft(data, n=n, axis=-1)
    ph = _phase(bins.astype(jnp.int32), k, n)  # [C, F]
    shifted = jnp.fft.irfft(X * ph, n=n, axis=-1)[:, :T].astype(data.dtype)
    return _vacated_fill(shifted, data, bins, padval)


def dedisperse(data, freqs, dt, dm, in_dm=0.0, padval=0):
    """Dedisperse at ``dm`` given current dm ``in_dm`` (reference
    formats/spectra.py:229-254, with the :37 dm-discard bug fixed).
    Shift values follow the shift_channels backend contract: bit-exact
    on CPU (gather); FFT f32 rounding on TPU unless
    PYPULSAR_TPU_SHIFT_BACKEND=gather (resolved per call; inside a
    user's enclosing jit it freezes at their trace time)."""
    backend = _resolve_shift_backend(padval, jnp.asarray(data).dtype)
    return _dedisperse_jit(data, freqs, dt, dm, in_dm, padval, backend)


@partial(jax.jit, static_argnames=("padval", "backend"))
def _dedisperse_jit(data, freqs, dt, dm, in_dm, padval, backend):
    bins = bin_delays(dm - in_dm, freqs, dt)
    return shift_channels(data, bins, padval, backend=backend)


def dedisperse_with_bins(data, bins, padval=0, n_fft=None):
    """Dedisperse with host-precomputed integer bin delays: the BIN MATH
    is the exact f64 reference path; shifted values follow the
    shift_channels backend contract (bit-exact gather on CPU, FFT f32
    rounding on TPU unless PYPULSAR_TPU_SHIFT_BACKEND=gather, resolved
    per call)."""
    return shift_channels(data, bins, padval, n_fft=n_fft)


def subband(data, freqs, dt, nsub, subdm=None, in_dm=0.0, padval=0):
    """Sum channel groups into ``nsub`` subbands, optionally dedispersing
    within each subband at ``subdm`` first (reference formats/spectra.py:96-138).

    Returns (subbanded_data[nsub, T], subband_center_freqs[nsub]).
    ``subdm``/``in_dm`` are traced (no per-DM recompile); only nsub/padval and
    the presence of subdm are static.
    """
    if subdm is None:
        return _subband_nodm(data, freqs, nsub)
    backend = _resolve_shift_backend(padval, jnp.asarray(data).dtype)
    return _subband_dm(data, freqs, dt, nsub, subdm, in_dm, padval, backend)


@partial(jax.jit, static_argnames=("nsub",))
def _subband_nodm(data, freqs, nsub):
    C, T = data.shape
    assert C % nsub == 0
    per = C // nsub
    hif = freqs[::per]
    lof = freqs[per - 1 :: per]
    ctr = 0.5 * (hif + lof)
    return data.reshape(nsub, per, T).sum(axis=1), ctr


@partial(jax.jit, static_argnames=("nsub", "padval", "backend"))
def _subband_dm(data, freqs, dt, nsub, subdm, in_dm, padval, backend):
    C, T = data.shape
    assert C % nsub == 0
    per = C // nsub
    hif = freqs[:: per]
    lof = freqs[per - 1 :: per]
    ctr = 0.5 * (hif + lof)
    ref = delay_from_DM(subdm - in_dm, hif)
    delays = delay_from_DM(subdm - in_dm, freqs)
    rel = delays - jnp.repeat(ref, per)
    bins = jnp.round(rel / dt).astype(jnp.int32)
    data = shift_channels(data, bins, padval, backend=backend)
    out = data.reshape(nsub, per, T).sum(axis=1)
    return out, ctr


@partial(jax.jit, static_argnames=("factor",))
def downsample(data, factor):
    """Co-add ``factor`` adjacent time bins; excess trimmed off the end
    (reference formats/spectra.py:329-351). ``factor`` static."""
    if factor <= 1:
        return data
    C, T = data.shape
    T2 = T // factor
    return data[:, : T2 * factor].reshape(C, T2, factor).sum(axis=-1)


@partial(jax.jit, static_argnames=("width", "padval"))
def smooth(data, width, padval=0):
    """RMS-preserving boxcar smooth of each channel: convolve with
    ones(width)/sqrt(width), 'same' alignment after padding ``width`` samples
    on both sides per ``padval`` mode (reference formats/spectra.py:262-303,
    itself from PRESTO single_pulse_search). ``width`` static."""
    if width <= 1:
        return data
    C, T = data.shape
    kernel = (jnp.ones(width, dtype=jnp.float32) / jnp.sqrt(float(width))).astype(data.dtype)
    if padval == "wrap":
        left, right = data[:, -width:], data[:, :width]
    elif padval == "mean":
        m = jnp.mean(data, axis=-1, keepdims=True)
        left = right = jnp.broadcast_to(m, (C, width))
    elif padval == "median":
        m = jnp.median(data, axis=-1, keepdims=True)
        left = right = jnp.broadcast_to(m, (C, width))
    else:
        left = right = jnp.full((C, width), padval, dtype=data.dtype)
    tosmooth = jnp.concatenate([left, data, right], axis=-1)
    # full f32 accumulation: XLA's default conv precision is bf16 on TPU
    sm = jax.vmap(
        lambda row: jnp.convolve(row, kernel, mode="same", precision=jax.lax.Precision.HIGHEST)
    )(tosmooth)
    return sm[:, width:-width]


@partial(jax.jit, static_argnames=("indep",))
def scaled(data, indep=False):
    """Subtract per-channel median; divide by global (or per-channel) std of
    the ORIGINAL data (reference formats/spectra.py:140-163)."""
    med = jnp.median(data, axis=-1, keepdims=True)
    std = jnp.std(data, axis=-1, keepdims=True) if indep else jnp.std(data)
    return (data - med) / std


@partial(jax.jit, static_argnames=("indep",))
def scaled2(data, indep=False):
    """Subtract per-channel min; divide by global (or per-channel) max of the
    ORIGINAL data (reference formats/spectra.py:165-188)."""
    mn = jnp.min(data, axis=-1, keepdims=True)
    mx = jnp.max(data, axis=-1, keepdims=True) if indep else jnp.max(data)
    return (data - mn) / mx


def channel_maskvals(data, maskval="median-mid80"):
    """Per-channel fill value for masking (reference formats/spectra.py:211-224).

    'median-mid80': median of the channel with top & bottom 10% of sorted
    samples removed (n = round(0.1*T); full median when n rounds to 0).
    """
    C, T = data.shape
    if maskval == "mean":
        return jnp.mean(data, axis=-1)
    if maskval == "median":
        return jnp.median(data, axis=-1)
    if maskval == "median-mid80":
        n = int(np.round(0.1 * T))
        if n == 0:
            return jnp.median(data, axis=-1)
        srt = jnp.sort(data, axis=-1)[:, n:-n]
        return jnp.median(srt, axis=-1)
    return jnp.full((C,), maskval, dtype=data.dtype)


@partial(jax.jit, static_argnames=("maskval",))
def masked(data, mask, maskval="median-mid80"):
    """Replace masked cells (mask True) with per-channel fill values
    (reference formats/spectra.py:190-227)."""
    vals = channel_maskvals(data, maskval)
    return jnp.where(mask, vals[:, None].astype(data.dtype), data)


@jax.jit
def zero_dm(data):
    """Zero-DM RFI filter: subtract the cross-channel mean from every time
    sample (reference bin/zero_dm_filter.py:30-39)."""
    return data - jnp.mean(data, axis=0, keepdims=True)


def trim(data, bins):
    """Drop ``bins`` spectra from the end (or start if negative); static.

    Parity exception: the reference's negative branch (formats/spectra.py:324-327)
    slices ``data[:, bins:]`` which KEEPS only the last |bins| samples and
    grows numspectra — contradicting its own docstring. We implement the
    documented intent: drop |bins| samples from the beginning.
    """
    if bins == 0:
        return data
    if bins > 0:
        return data[:, :-bins]
    return data[:, -bins:]


# ---------------------------------------------------------------------------
# detection / reduction kernels used by the sweep engine
# ---------------------------------------------------------------------------


@jax.jit
def dedispersed_timeseries(data, bins):
    """Fold channels into a dedispersed time series: sum over channels after
    per-channel circular left-shift. The hot kernel of the DM sweep."""
    return rotate_rows(data, bins).sum(axis=0)


@partial(jax.jit, static_argnames=("widths",))
def boxcar_snr(ts, widths):
    """Matched-filter boxcar SNRs of a 1-D time series.

    Normalizes ts to zero median / unit std, then for each width w convolves
    with ones(w)/sqrt(w) (the RMS-preserving kernel of reference
    formats/spectra.py:283 / formats/pulse.py smooth) and takes the max.
    Returns (best_snr_per_width[len(widths)], argmax_per_width[len(widths)]).
    ``widths`` is a static tuple.
    """
    med = jnp.median(ts)
    std = jnp.std(ts)
    norm = (ts - med) / jnp.where(std == 0, 1.0, std)
    cs = jnp.concatenate([jnp.zeros(1, norm.dtype), jnp.cumsum(norm)])
    snrs = []
    idxs = []
    n = norm.shape[0]
    for w in widths:
        sums = (cs[w:] - cs[:-w]) / jnp.sqrt(float(w))
        snrs.append(jnp.max(sums))
        idxs.append(jnp.argmax(sums))
    return jnp.stack(snrs), jnp.stack(idxs)
