"""Complex-array boundary helpers for backends with incomplete buffer
support.

The axon remote-TPU platform cannot move complex buffers across any
executable boundary: host->device transfer (device_put / jit arguments),
device->host pulls (np.asarray of a complex output), and handing one
program's complex output to another program all raise UNIMPLEMENTED
(observed on v5e, bench r3). Complex arithmetic *inside* a single
compiled program is fully supported.

Consequently the framework's rule is: complex64 lives only inside jit.
Every jit signature that logically takes/returns a complex array takes/
returns separate real and imaginary float32 planes instead, recombined
with ``jax.lax.complex`` on entry and split with ``.real``/``.imag``
before returning. These helpers cover the host side of that contract.
"""

from __future__ import annotations

import jax
import numpy as np

from pypulsar_tpu.obs import telemetry

__all__ = ["split_complex", "to_host_complex", "join_planes", "pull_host"]


def pull_host(*arrays):
    """Fetch several device arrays to host in ONE batched transfer.

    Through the axon tunnel every individual ``np.asarray(device_array)``
    pull pays its own ~65 ms roundtrip; ``jax.device_get`` issues the
    fetches together and waits once (measured on chip: 4 small arrays,
    262 ms per-array vs 70 ms batched — BENCHNOTES.md round 4). Use this
    for every multi-output pull on a hot path. Always returns a tuple
    (same arity as the arguments), so star-splatted call sites unpack
    predictably even for one output. Under an active telemetry session
    the pull is accounted to the ``d2h.bytes``/``d2h.pulls`` counters."""
    if telemetry.is_active():
        telemetry.counter("d2h.bytes", sum(
            int(getattr(a, "nbytes", 0) or 0) for a in arrays))
        telemetry.counter("d2h.pulls")
    return jax.device_get(arrays)


def join_planes(re, im):
    """Recombine float planes into complex — INSIDE jit only (the result
    must not cross an executable boundary). The canonical other half of
    :func:`split_complex`: plane order is (real, imaginary)."""
    import jax.lax

    return jax.lax.complex(re, im)


def split_complex(arr):
    """(re, im) float32 planes of a possibly-complex array.

    Host arrays split in NumPy; device arrays (already past a boundary,
    so CPU/TPU-internal backends only) split with eager ``.real``/
    ``.imag``, which the axon platform supports. Real input gets a zero
    imaginary plane."""
    if isinstance(arr, jax.Array):
        import jax.numpy as jnp

        if jnp.iscomplexobj(arr):
            return (arr.real.astype(jnp.float32),
                    arr.imag.astype(jnp.float32))
        return arr.astype(jnp.float32), jnp.zeros_like(arr, jnp.float32)
    a = np.asarray(arr)
    if np.iscomplexobj(a):
        return (np.ascontiguousarray(a.real, dtype=np.float32),
                np.ascontiguousarray(a.imag, dtype=np.float32))
    return a.astype(np.float32), np.zeros_like(a, dtype=np.float32)


def to_host_complex(re, im) -> np.ndarray:
    """Host complex64 from separate (device or host) float planes — the
    device->host pull happens per real plane, which every backend
    supports; both planes fetch in one batched transfer (pull_host)."""
    re, im = pull_host(re, im)
    return (np.asarray(re, dtype=np.float32)
            + 1j * np.asarray(im, dtype=np.float32)).astype(np.complex64)
