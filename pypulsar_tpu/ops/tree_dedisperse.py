"""Tree dedispersion: O(log2 nchan) shared-work sweep over ALL DM trials.

Why: every other sweep engine computes each DM trial independently —
per output sample the two-stage engines pay ``G*C`` stage-1 adds plus
``D*S`` stage-2 adds (parallel/sweep.py), and PR 2's roofline proved the
accel stage already runs at 85% of its FFT ceiling, so the remaining
order of magnitude at production DM counts (thousands of trials, not the
toy 16) must come from *sharing work between trials*. The Fast DM
Transform / tree recurrences (PAPERS.md 1201.5380 "Accelerating
incoherent dedispersion"; 2311.05341 "Accelerating Dedispersion using
Many-Core Architectures") compute all trials together through log2(nchan)
pairwise subband-merge levels: a partial sum over a 2w-channel block is
one add of two w-channel partial sums, and trials whose per-channel
shifts agree on a block SHARE that block's row instead of re-summing it.

The classic FDMT buys its complexity bound with a linear-delay
approximation inside each block. This engine does NOT approximate: the
per-level shift tables are derived from the EXACT integer shifts the
direct engines apply (``stage1_bins + stage2_bins``, i.e. the
numpy_ref.bin_delays rounding, split exactly as the two-stage plan splits
it), and the merge recurrence is exact by construction —

    row(block, v)[t] = sum_{c in block} data[c, t + P_v(c)]

where each variant profile ``P_v`` is a *normalized* (min-zero) restriction
of some trial's exact shift vector to the block. Merging blocks L|R:
``P_v`` restricted to L is itself a variant ``vL`` of L shifted by
``offA = min_L P_v`` and likewise for R, so

    row(LR, v)[t] = row(L, vL)[t + offA] + row(R, vR)[t + offB]

— one batched gather+add per level over the previous level's rows, with
static-shape tables and dynamic gather indices, expressed as a
``lax.scan`` over the levels. The final **exact-shift snap stage** maps
trial d to its top-level variant row read at offset ``min_c shift[d, c]``:
every channel's total shift in trial d's series is then BYTE-FOR-BIT the
same ``s1 + s2`` the gather/scan/fourier engines apply. What differs is
only the f32 *summation tree* (balanced pairwise vs reshape-reduce),
which lands inside the sweep's existing ≤2e-6 relative-SNR parity
contract (tests/test_sweep.py::test_tree_engine_snr_tolerance).

Work accounting (the structural counters tools/dedisp_roofline.py and
``bench.py --dedisp-tree`` report): per output sample the tree performs
``sum_l R_l`` adds, where ``R_l`` is level l's merged-row count — bounded
by ``nblocks_l * min(D_distinct, span_l + 1)`` with ``span_l`` the
dispersion-delay spread across a level-l block. At the FDMT-regime
diagonal (trial spacing ~ the delay step, delay span ~ nchan) that is
~``max(nchan, span) * log2(nchan)`` for ALL trials, versus
``D * (C/g + S)`` for the two-stage direct engine and ``D * C`` naive —
and with the delay span held fixed it scales ~log2(nchan) while direct
scales ~nchan. Because the tables are deduplicated against the ACTUAL
trial list, toy grids collapse to near-direct row counts instead of
paying the full FDMT delay enumeration.

Host/device split: the merge tables are built host-side (NumPy, cached —
``PYPULSAR_TPU_TREE_PLAN_CACHE`` entries) because deduplication is
data-dependent; the kernels are pure static-shape scans, so everything
jits with dynamic table CONTENT and static table SHAPE. The engine
therefore dispatches from the Python wrappers in parallel/sweep.py
(``sweep_chunk`` / ``dedisperse_series_chunk``), never from inside a
traced ``_sweep_chunk_impl``.

Sharding: the per-trial value of a tree row depends only on that trial's
own shift vector (the merge structure over channels is fixed), so a
'dm'-mesh shard that builds its OWN tables for its local trial groups
produces rows bit-identical to the unsharded engine's — the same
device-count-independence contract the other engines' sharded paths
carry (tests assert array_equal, not allclose).

Reference treatment: nonexistent (the reference rolls channels one trial
at a time, formats/spectra.py:54-94; PRESTO's prepsubband shares work
only through the two-stage subband split this engine's exact tables
inherit).
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from functools import lru_cache, partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pypulsar_tpu.obs import telemetry
from pypulsar_tpu.ops.pallas_kernels import boxcar_stats
from pypulsar_tpu.tune import knobs

__all__ = [
    "TreePlan",
    "plan_from_bins",
    "sweep_chunk_tree",
    "dedisperse_series_tree",
    "make_sharded_tree_sweep_chunk",
    "make_sharded_tree_series_chunk",
]


class TreePlan:
    """Host-built merge-tree tables for one (stage1_bins, stage2_bins)
    shift set.

    tabs[4, NL, R] int32   per-level (srcA, srcB, offA, offB); rows past a
                           level's real count (and passthrough srcB) point
                           at the constant zero row ``R``
    trial_row[D] int32     top-level row of each trial (group-major order)
    trial_off[D] int32     the snap offset: min_c of the trial's exact
                           per-channel shift (its profile is stored
                           min-normalized)
    pad                    static shift bound for the per-level slices
                           (max exact total shift)
    adds_per_sample        sum of real (two-child) merges over all levels
                           — the structural work counter
    """

    def __init__(self, tabs, trial_row, trial_off, pad, group_size,
                 rows, n_levels, adds_per_sample, rows_per_level,
                 n_channels):
        self.tabs = tabs
        self.trial_row = trial_row
        self.trial_off = trial_off
        self.pad = int(pad)
        self.group_size = int(group_size)
        self.rows = int(rows)
        self.n_levels = int(n_levels)
        self.adds_per_sample = int(adds_per_sample)
        self.rows_per_level = tuple(int(r) for r in rows_per_level)
        self.n_channels = int(n_channels)
        self.n_trials = int(len(trial_row))
        self._dev = None  # lazily cached device copies of the tables

    def device_tables(self):
        """(tabs, trial_row, trial_off) as device arrays, converted once
        so the per-chunk dispatches of a streamed sweep reuse the same
        buffers instead of re-shipping the tables every chunk."""
        if self._dev is None:
            self._dev = (jnp.asarray(self.tabs),
                         jnp.asarray(self.trial_row),
                         jnp.asarray(self.trial_off))
        return self._dev

    def state_bytes(self, chunk_len: int) -> int:
        """f32 bytes of the [R+1, chunk_len] merge-state buffer one
        dispatch keeps resident (the ``tree.bytes_on_device`` counter)."""
        return 4 * (self.rows + 1) * int(chunk_len)


def _build_plan(s1: np.ndarray, s2: np.ndarray) -> TreePlan:
    """Build the merge tables from the exact two-stage shift tables.

    ``s1[G, C]`` / ``s2[G, g, S]`` are the plan's integer shifts; the
    exact per-trial per-channel total is ``s1[g(d), c] + s2[g(d), t(d),
    c // per]`` — the same sum every other engine applies."""
    s1 = np.asarray(s1, dtype=np.int64)
    s2 = np.asarray(s2, dtype=np.int64)
    G, C = s1.shape
    _, g, S = s2.shape
    per = C // S
    D = G * g
    tot = (s1[:, None, :] + np.repeat(s2, per, axis=2)).reshape(D, C)

    # level 0: one row per channel; a trial's "variant" of channel c is
    # the row itself, its base the exact shift (profiles are min-zero
    # normalized, and a single channel's profile is trivially {0})
    assign = np.broadcast_to(np.arange(C, dtype=np.int64), (D, C)).copy()
    base = tot.copy()
    ZERO = -1  # sentinel for "the constant zero row"; patched to R below
    levels = []
    rows_per_level = []
    adds = 0
    rows_max = C
    nb = C
    while nb > 1:
        nb_new = (nb + 1) // 2
        new_assign = np.empty((D, nb_new), dtype=np.int64)
        new_base = np.empty((D, nb_new), dtype=np.int64)
        srcA: list = []
        srcB: list = []
        offA: list = []
        offB: list = []
        for p in range(nb_new):
            lc, rc = 2 * p, 2 * p + 1
            k0 = len(srcA)
            if rc >= nb:
                # odd block count: the last block passes through (add of
                # the zero row — structurally zero adds)
                uniq, inv = np.unique(assign[:, lc], return_inverse=True)
                srcA.extend(int(u) for u in uniq)
                srcB.extend(ZERO for _ in uniq)
                offA.extend(0 for _ in uniq)
                offB.extend(0 for _ in uniq)
                new_assign[:, p] = k0 + inv
                new_base[:, p] = base[:, lc]
                continue
            bl, br = base[:, lc], base[:, rc]
            nbase = np.minimum(bl, br)
            # parent variant identity: (left variant, right variant,
            # child offsets after re-normalization) — trials sharing the
            # key share the parent row, which is where the work sharing
            # happens; offsets are >= 0 by the min-normalization even
            # where per-term rounding makes the raw shifts non-monotonic
            key = np.stack([assign[:, lc], assign[:, rc],
                            bl - nbase, br - nbase], axis=1)
            uniq, inv = np.unique(key, axis=0, return_inverse=True)
            srcA.extend(int(u) for u in uniq[:, 0])
            srcB.extend(int(u) for u in uniq[:, 1])
            offA.extend(int(u) for u in uniq[:, 2])
            offB.extend(int(u) for u in uniq[:, 3])
            adds += len(uniq)
            new_assign[:, p] = k0 + inv
            new_base[:, p] = nbase
        levels.append((np.asarray(srcA, dtype=np.int64),
                       np.asarray(srcB, dtype=np.int64),
                       np.asarray(offA, dtype=np.int64),
                       np.asarray(offB, dtype=np.int64)))
        rows_per_level.append(len(srcA))
        rows_max = max(rows_max, len(srcA))
        assign, base, nb = new_assign, new_base, nb_new

    NL = len(levels)
    R = rows_max
    tabs = np.empty((4, max(NL, 1), R), dtype=np.int32)
    # unused table cells read the zero row at shift 0 (0 + 0 rows): the
    # scan keeps static [R] width while real row counts vary per level
    tabs[0], tabs[1] = R, R
    tabs[2], tabs[3] = 0, 0
    for li, (a, b, oa, ob) in enumerate(levels):
        n = len(a)
        tabs[0, li, :n] = np.where(a < 0, R, a)
        tabs[1, li, :n] = np.where(b < 0, R, b)
        tabs[2, li, :n] = oa
        tabs[3, li, :n] = ob
    if NL == 0:  # single channel: no merges, trials snap straight to it
        tabs = tabs[:, :0]
    return TreePlan(
        tabs=tabs,
        trial_row=assign[:, 0].astype(np.int32),
        trial_off=base[:, 0].astype(np.int32),
        pad=max(int(tot.max(initial=0)), 0),
        group_size=g,
        rows=R,
        n_levels=NL,
        adds_per_sample=adds,
        rows_per_level=rows_per_level,
        n_channels=C,
    )


# Plan cache: keyed by a digest of the exact shift tables so the
# streamed sweep's per-chunk dispatches (and OOM-halved group slices,
# which arrive as table SLICES) reuse their host-built tables. Bounded
# because each entry holds ~NL*R*16 bytes of tables: the knob trades
# rebuild time against host RAM when many distinct slicings are live.
_PLAN_CACHE: "OrderedDict[bytes, TreePlan]" = OrderedDict()


def _plan_cache_size() -> int:
    # registry read is typo-tolerant (bad value -> declared default 8)
    return max(1, int(knobs.env_int("PYPULSAR_TPU_TREE_PLAN_CACHE")))


def _digest(s1: np.ndarray, s2: np.ndarray) -> bytes:
    h = hashlib.sha256()
    for a in (s1, s2):
        h.update(np.int64(a.shape).tobytes())
        h.update(np.ascontiguousarray(a, dtype=np.int32).tobytes())
    return h.digest()


def plan_from_bins(stage1_bins, stage2_bins) -> TreePlan:
    """Cached :class:`TreePlan` for these exact shift tables (device
    arrays accepted — the tables are KBs)."""
    s1 = np.asarray(stage1_bins)
    s2 = np.asarray(stage2_bins)
    key = _digest(s1, s2)
    plan = _PLAN_CACHE.pop(key, None)
    if plan is None:
        plan = _build_plan(s1, s2)
    _PLAN_CACHE[key] = plan  # (re)insert as most-recent
    while len(_PLAN_CACHE) > _plan_cache_size():
        _PLAN_CACHE.popitem(last=False)
    return plan


# ---------------------------------------------------------------------------
# device kernels
# ---------------------------------------------------------------------------


def _shift_rows(rows, offs, pad: int, L: int):
    """rows[N, L] shifted left per-row by offs (0 <= off <= pad), zero
    fill on the right — the level-merge move. The zero-extended reads
    can only reach the tail region the final snap never consumes (the
    chunk carries >= ``pad`` overlap samples past every payload)."""
    if pad:
        rows = jnp.pad(rows, ((0, 0), (0, pad)))
    return jax.vmap(
        lambda r, s: jax.lax.dynamic_slice(r, (s,), (L,))
    )(rows, offs.astype(jnp.int32))


def _tree_rows_impl(data, tabs, pad: int):
    """Run the merge scan: data[C, L] -> state[R+1, L] of top-level rows
    (row R is the constant zero row every passthrough/padding entry
    reads)."""
    C, L = data.shape
    R = tabs.shape[2]
    state = jnp.zeros((R + 1, L), jnp.float32).at[:C].set(
        data.astype(jnp.float32))
    zero_row = jnp.zeros((1, L), jnp.float32)

    def level(st, t):
        a, b, oa, ob = t[0], t[1], t[2], t[3]
        new = _shift_rows(st[a], oa, pad, L) + _shift_rows(st[b], ob,
                                                           pad, L)
        return jnp.concatenate([new, zero_row], axis=0), None

    if tabs.shape[1]:
        state, _ = jax.lax.scan(level, state, tabs.transpose(1, 0, 2))
    return state


def _snap(state, trial_row, trial_off, out_len: int):
    """The exact-shift snap: trial d's series is its top row read at its
    min-shift offset, so channel c contributes data[c, t + (off + P(c)))]
    = data[c, t + s1 + s2] exactly."""
    return jax.vmap(
        lambda r, o: jax.lax.dynamic_slice(state[r], (o,), (out_len,))
    )(trial_row, trial_off.astype(jnp.int32))


@partial(jax.jit, static_argnames=("out_len", "pad"))
def _tree_series(data, tabs, trial_row, trial_off, out_len, pad):
    state = _tree_rows_impl(data, tabs, pad)
    return _snap(state, trial_row, trial_off, out_len)


def _tree_stats_impl(data, tabs, trial_row, trial_off, out_len, widths,
                     stat_len, group, pad):
    state = _tree_rows_impl(data, tabs, pad)
    D = trial_row.shape[0]
    G = D // group
    tr = trial_row.reshape(G, group)
    to = trial_off.reshape(G, group)

    def per_group(carry, xs):
        r, o = xs
        ts = _snap(state, r, o, out_len)  # [g, out_len]
        s, ss, mb_g, ab_g = boxcar_stats(ts, widths, stat_len)
        return carry, (s, ss, mb_g, ab_g)

    _, (s, ss, mb, ab) = jax.lax.scan(per_group, 0, (tr, to))
    return (
        s.reshape(D),
        ss.reshape(D),
        mb.reshape(D, len(widths)),
        ab.reshape(D, len(widths)),
    )


_tree_stats = jax.jit(
    _tree_stats_impl,
    static_argnames=("out_len", "widths", "stat_len", "group", "pad"),
)


def _note_dispatch(plan: TreePlan, chunk_len: int, n_samples: int,
                   dev_ids=None) -> None:
    """Host-side structural counters per dispatch (kernels cannot emit
    from inside jit): merge depth, shared-work adds actually performed
    for this chunk's samples, and the resident merge-state bytes —
    stamped per device under a mesh per the PR 6 lease contract."""
    if not telemetry.is_active():
        return
    telemetry.gauge("tree.merge_levels", plan.n_levels)
    adds = plan.adds_per_sample * int(n_samples)
    state_b = plan.state_bytes(chunk_len)
    telemetry.counter("tree.adds_total", adds)
    telemetry.counter("tree.bytes_on_device", state_b)
    for d in dev_ids or ():
        telemetry.counter(f"device{d}.tree.adds_total", adds)
        telemetry.counter(f"device{d}.tree.bytes_on_device", state_b)


def sweep_chunk_tree(data, stage1_bins, stage2_bins, out_len: int,
                     widths: Tuple[int, ...], stat_len: int):
    """Tree-engine twin of ``parallel.sweep.sweep_chunk``: per-trial
    (sum, sumsq, maxbox, argbox) for one chunk, all trials through the
    shared merge tree + exact snap."""
    plan = plan_from_bins(stage1_bins, stage2_bins)
    _note_dispatch(plan, data.shape[-1], stat_len)
    tabs, tr, to = plan.device_tables()
    return _tree_stats(data, tabs, tr, to, out_len, tuple(widths),
                       stat_len, plan.group_size, plan.pad)


def dedisperse_series_tree(data, stage1_bins, stage2_bins, out_len: int):
    """Tree-engine twin of ``parallel.sweep.dedisperse_series_chunk``:
    the raw [D, out_len] dedispersed series for one chunk — the kernel
    the streamed .dat writer, the accel handoff and the specfuse stitch
    consume when ``engine='tree'``."""
    plan = plan_from_bins(stage1_bins, stage2_bins)
    _note_dispatch(plan, data.shape[-1], out_len)
    tabs, tr, to = plan.device_tables()
    return _tree_series(data, tabs, tr, to, out_len, plan.pad)


# ---------------------------------------------------------------------------
# 'dm'-mesh sharding: per-device tables, stacked + padded to one shape
# ---------------------------------------------------------------------------


def _stack_shard_plans(s1: np.ndarray, s2: np.ndarray, k: int):
    """Build one TreePlan per device shard of the trial groups and stack
    the tables to a common [k, NL, 4, R] shape (per-device zero-row
    indices remapped to the common R). Returns (plans, tabs, trial_row,
    trial_off, pad) as host arrays, trial arrays flat [D] in group
    order so a P('dm') sharding gives each device its own trials."""
    G = s1.shape[0]
    if G % k:
        raise ValueError(f"group count {G} must divide the mesh 'dm' "
                         f"axis {k}; use make_sweep_plan(pad_groups_to=...)")
    per = G // k
    plans = [plan_from_bins(s1[i * per:(i + 1) * per],
                            s2[i * per:(i + 1) * per]) for i in range(k)]
    NL = max(p.tabs.shape[1] for p in plans)
    R = max(p.rows for p in plans)
    pad = max(p.pad for p in plans)
    tabs = np.empty((k, NL, 4, R), dtype=np.int32)
    tabs[:, :, 0:2] = R
    tabs[:, :, 2:4] = 0
    for i, p in enumerate(plans):
        t = p.tabs  # [4, NLp, Rp]
        nl, r = t.shape[1], t.shape[2]
        src = np.where(t[0:2] == p.rows, R, t[0:2])
        tabs[i, :nl, 0:2, :r] = src.transpose(1, 0, 2)
        tabs[i, :nl, 2:4, :r] = t[2:4].transpose(1, 0, 2)
    trial_row = np.concatenate([p.trial_row for p in plans])
    trial_off = np.concatenate([p.trial_off for p in plans])
    return plans, tabs, trial_row, trial_off, pad


@lru_cache(maxsize=32)
def _sharded_tree_fn(mesh, out_len, widths, stat_len, group, pad,
                     series: bool):
    """Compiled shard_map'd tree kernel for one (mesh, geometry) — each
    device runs the scan over ITS stacked table slice and its local
    trials; rows concatenate in group order (P('dm')), bit-identical to
    the unsharded engine per trial."""
    from jax.sharding import PartitionSpec as P

    from pypulsar_tpu.parallel.sweep import shard_map_compat

    def impl(data, tabs, trial_row, trial_off):
        t = tabs[0].transpose(1, 0, 2)  # local [NL, 4, R] -> [4, NL, R]
        if series:
            state = _tree_rows_impl(data, t, pad)
            return _snap(state, trial_row, trial_off, out_len)
        return _tree_stats_impl(data, t, trial_row, trial_off, out_len,
                                widths, stat_len, group, pad)

    out = P("dm") if series else (P("dm"),) * 4
    fn = shard_map_compat(impl, mesh=mesh,
                          in_specs=(P(), P("dm"), P("dm"), P("dm")),
                          out_specs=out)
    return jax.jit(fn)


def _make_sharded_tree(mesh, out_len, widths, stat_len, series: bool):
    from jax.sharding import NamedSharding, PartitionSpec as P

    k = int(mesh.shape["dm"])
    dev_ids = [int(getattr(d, "id", -1)) for d in mesh.devices.flat]
    cache: "OrderedDict[bytes, tuple]" = OrderedDict()

    def fn(data, stage1_bins, stage2_bins):
        s1 = np.asarray(stage1_bins)
        s2 = np.asarray(stage2_bins)
        key = _digest(s1, s2)
        entry = cache.pop(key, None)
        if entry is None:
            plans, tabs, tr, to, pad = _stack_shard_plans(s1, s2, k)
            spec = NamedSharding(mesh, P("dm"))
            entry = (
                [p for p in plans],
                jax.device_put(jnp.asarray(tabs), spec),
                jax.device_put(jnp.asarray(tr), spec),
                jax.device_put(jnp.asarray(to), spec),
                pad,
            )
        cache[key] = entry
        while len(cache) > _plan_cache_size():
            cache.popitem(last=False)
        plans, tabs_d, tr_d, to_d, pad = entry
        for p, d in zip(plans, dev_ids):
            _note_dispatch(p, data.shape[-1],
                           out_len if series else stat_len, dev_ids=[d])
        run = _sharded_tree_fn(mesh, out_len, widths, stat_len,
                               plans[0].group_size, pad, series)
        return run(data, tabs_d, tr_d, to_d)

    return fn


def make_sharded_tree_sweep_chunk(mesh, out_len: int,
                                  widths: Tuple[int, ...], stat_len: int):
    """Tree-engine twin of ``parallel.sweep.make_sharded_sweep_chunk``
    — returns ``fn(data, stage1_bins, stage2_bins)``; the tables may be
    group slices (the OOM-halving contract)."""
    return _make_sharded_tree(mesh, out_len, tuple(widths), stat_len,
                              series=False)


def make_sharded_tree_series_chunk(mesh, out_len: int):
    """Tree-engine twin of ``parallel.sweep.make_sharded_series_chunk``."""
    return _make_sharded_tree(mesh, out_len, (1,), 0, series=True)
