from pypulsar_tpu.ops import kernels  # noqa: F401

# numpy_ref (the scipy-dependent golden twins) is imported lazily by tests;
# not re-exported here to keep scipy out of the production import path.
