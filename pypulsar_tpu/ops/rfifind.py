"""TPU-native RFI mask generation — a PRESTO ``rfifind`` equivalent.

The reference *consumes* rfifind ``.mask`` files (bin/waterfaller.py:21,
28-48; ``rfifind`` imported 3x per SURVEY.md §2.5) but the mask generator
itself is PRESTO's external C program — one of the L0 native dependencies
SURVEY.md says must be replaced. This module closes that gap so a user can
go raw file -> mask -> masked pipeline without PRESTO installed:

  1. device pass (jit): per-(interval, channel) block statistics — mean,
     standard deviation, and the maximum normalized Fourier power of the
     block (periodic-interference detector);
  2. host pass: iterative sigma clipping of the small [nint, nchan] stat
     tables along both axes (each channel's timeline and each interval's
     bandpass), PRESTO-style;
  3. reduction to the mask products: whole channels / whole intervals are
     zapped when more than ``chanfrac`` / ``intfrac`` of their blocks are
     flagged, the remainder becomes the per-interval zap lists; written in
     the reference binary layout by io.rfimask.write_mask.

The Fourier detector pads each block to a power of two before the rfft —
non-power-of-two FFTs lower to a dense O(L^2) DFT matmul on this TPU
toolchain (BENCHNOTES.md). Padding only dilutes a tone's power by the duty
factor, which the significance threshold absorbs.

Statistics are flagged against a robust center/scale (median and
interquartile-range-derived sigma) so that the estimate itself is immune
to the outliers being hunted; the max-power test uses the exponential null
distribution of normalized powers: P(max over B bins > p) ~ B*exp(-p),
thresholded at the single-sided Gaussian tail probability of
``freq_sigma``.
"""

from __future__ import annotations

import dataclasses
import math
import warnings
from functools import partial
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pypulsar_tpu.obs import telemetry
from pypulsar_tpu.ops import transfer
from pypulsar_tpu.ops.fourier_dedisperse import fourier_chunk_len

__all__ = [
    "RfiStats",
    "block_stats",
    "block_stats_numpy",
    "clip_stats",
    "mask_products",
    "rfifind",
]


@partial(jax.jit, static_argnames=("pts", "n_fft"))
def _block_stats_impl(data, pts: int, n_fft: int):
    """data[C, nint*pts] -> (mean[nint, C], std[nint, C], maxpow[nint, C]).

    maxpow is the largest normalized power over the block's positive-
    frequency bins: powers / (their own mean), so a flat (white) block
    scores ~ln(B) and a coherent tone scores its SNR^2-scale power —
    interval-to-interval gain drifts cancel out.
    """
    C = data.shape[0]
    nint = data.shape[1] // pts
    blocks = data[:, : nint * pts].reshape(C, nint, pts)
    mean = jnp.mean(blocks, axis=2)
    # f32 two-pass variance: centered sum of squares (one-pass sum/sumsq
    # catastrophically cancels for offset-dominated 8-bit data)
    centered = blocks - mean[:, :, None]
    var = jnp.mean(centered * centered, axis=2)
    std = jnp.sqrt(var)
    spec = jnp.fft.rfft(centered, n=n_fft, axis=2)
    pow_ = spec.real * spec.real + spec.imag * spec.imag
    pow_ = pow_[:, :, 1:]  # DC removed by centering; drop it anyway
    norm = jnp.mean(pow_, axis=2, keepdims=True)
    maxpow = jnp.max(pow_ / jnp.maximum(norm, 1e-30), axis=2)
    return mean.T, std.T, maxpow.T


def block_stats(data, pts: int):
    """Device per-block stats of ``data[C, T]`` (whole intervals only)."""
    n_fft = fourier_chunk_len(pts)
    return _block_stats_impl(jnp.asarray(data, jnp.float32), pts, n_fft)


def block_stats_numpy(data: np.ndarray, pts: int):
    """float64 NumPy twin of block_stats (parity tests)."""
    C = data.shape[0]
    nint = data.shape[1] // pts
    blocks = data[:, : nint * pts].reshape(C, nint, pts).astype(np.float64)
    mean = blocks.mean(axis=2)
    centered = blocks - mean[:, :, None]
    std = np.sqrt((centered * centered).mean(axis=2))
    spec = np.fft.rfft(centered, n=fourier_chunk_len(pts), axis=2)
    pow_ = (spec.real**2 + spec.imag**2)[:, :, 1:]
    norm = np.maximum(pow_.mean(axis=2, keepdims=True), 1e-30)
    maxpow = (pow_ / norm).max(axis=2)
    return mean.T, std.T, maxpow.T


@dataclasses.dataclass
class RfiStats:
    """Per-(interval, channel) statistics of an observation, in *file*
    channel order (the .mask convention; io/rfimask.py docstring)."""

    mean: np.ndarray  # [nint, nchan]
    std: np.ndarray
    maxpow: np.ndarray
    ptsperint: int
    dtint: float
    lofreq: float
    df: float
    mjd: float = 0.0
    # set by rfifind(): fraction of (interval, channel) cells the final
    # mask products zap (None until products are computed)
    mask_coverage: Optional[float] = None

    @property
    def nint(self) -> int:
        return self.mean.shape[0]

    @property
    def nchan(self) -> int:
        return self.mean.shape[1]

    def save(self, fn: str) -> str:
        """Sidecar stats file (our own npz schema — PRESTO's .stats binary
        carries the same tables; kept separate so the .mask stays
        reference-layout)."""
        np.savez(fn, mean=self.mean, std=self.std, maxpow=self.maxpow,
                 ptsperint=self.ptsperint, dtint=self.dtint,
                 lofreq=self.lofreq, df=self.df, mjd=self.mjd,
                 mask_coverage=(np.nan if self.mask_coverage is None
                                else self.mask_coverage))
        return fn

    @classmethod
    def load(cls, fn: str) -> "RfiStats":
        with np.load(fn) as z:
            cov = float(z["mask_coverage"]) if "mask_coverage" in z else np.nan
            return cls(mean=z["mean"], std=z["std"], maxpow=z["maxpow"],
                       ptsperint=int(z["ptsperint"]), dtint=float(z["dtint"]),
                       lofreq=float(z["lofreq"]), df=float(z["df"]),
                       mjd=float(z["mjd"]),
                       mask_coverage=None if np.isnan(cov) else cov)


def _robust_center_scale(x: np.ndarray, good: np.ndarray, axis: int):
    """(median, sigma) along ``axis`` using only ``good`` cells; sigma from
    the 25-75 interquartile range (IQR/1.349 estimates a Gaussian sigma
    robustly). Cells where everything is flagged get sigma=inf (no new
    flags can arise from them)."""
    masked = np.where(good, x, np.nan)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)  # all-NaN slices
        med = np.nanmedian(masked, axis=axis, keepdims=True)
        q75 = np.nanpercentile(masked, 75, axis=axis, keepdims=True)
        q25 = np.nanpercentile(masked, 25, axis=axis, keepdims=True)
    med = np.where(np.isnan(med), 0.0, med)
    sigma = (q75 - q25) / 1.349
    sigma = np.where(np.isnan(sigma) | (sigma <= 0), np.inf, sigma)
    return med, sigma


def clip_stats(
    stats: RfiStats,
    time_sigma: float = 10.0,
    freq_sigma: float = 4.0,
    max_iter: int = 10,
) -> np.ndarray:
    """Boolean flag table [nint, nchan] (True = bad block).

    Time-domain test: a block's mean or std is an outlier at
    ``time_sigma`` against its channel's timeline (axis 0) or its
    interval's bandpass (axis 1). Fourier test: the block's max normalized
    power exceeds the exponential-null threshold at the ``freq_sigma``
    Gaussian-equivalent tail probability. Clipping iterates so that loud
    blocks do not inflate the scale estimate that judges the others.
    """
    mean, std, maxpow = stats.mean, stats.std, stats.maxpow
    # exponential null for the max of B normalized powers (mean power = 1):
    # P(max > p) ~ B * exp(-p)  ->  p_thresh = ln(B / q)
    B = fourier_chunk_len(stats.ptsperint) // 2
    q = 0.5 * math.erfc(freq_sigma / math.sqrt(2.0))
    power_thresh = math.log(B / max(q, 1e-300))
    flags = maxpow > power_thresh

    # flags accumulate monotonically: a fully-flagged row/column has no
    # good cells left to estimate a scale from (sigma=inf), so re-deriving
    # flags from scratch each pass would silently unflag it
    for _ in range(max_iter):
        good = ~flags
        new = flags.copy()
        for x in (mean, std):
            for axis in (0, 1):
                med, sigma = _robust_center_scale(x, good, axis)
                new |= np.abs(x - med) > time_sigma * sigma
        if np.array_equal(new, flags):
            break
        flags = new
    return flags


def mask_products(
    flags: np.ndarray,
    chanfrac: float = 0.7,
    intfrac: float = 0.3,
    extra_zap_chans: Sequence[int] = (),
    extra_zap_ints: Sequence[int] = (),
) -> Tuple[List[int], List[int], List[List[int]]]:
    """Reduce the flag table to (zap_chans, zap_ints, zap_chans_per_int).

    A channel flagged in more than ``chanfrac`` of intervals is zapped
    outright (likewise intervals at ``intfrac``) — PRESTO's -chanfrac /
    -intfrac semantics; remaining flags become per-interval lists. The
    per-interval lists exclude globally zapped channels (the reader
    re-unions them), keeping the file small.
    """
    nint, nchan = flags.shape
    for c in extra_zap_chans:
        if not 0 <= int(c) < nchan:
            raise ValueError(
                f"zap channel {c} outside [0, {nchan}) — indices are in "
                f"mask channel order (channel 0 = lowest frequency)")
    for i in extra_zap_ints:
        if not 0 <= int(i) < nint:
            raise ValueError(f"zap interval {i} outside [0, {nint})")
    chan_bad = flags.mean(axis=0)
    int_bad = flags.mean(axis=1)
    zap_chans = set(np.nonzero(chan_bad > chanfrac)[0].tolist())
    zap_chans.update(int(c) for c in extra_zap_chans)
    zap_ints = set(np.nonzero(int_bad > intfrac)[0].tolist())
    zap_ints.update(int(i) for i in extra_zap_ints)
    per_int: List[List[int]] = []
    for i in range(nint):
        if i in zap_ints:
            per_int.append([])
            continue
        chans = np.nonzero(flags[i])[0]
        per_int.append([int(c) for c in chans if int(c) not in zap_chans])
    return sorted(zap_chans), sorted(zap_ints), per_int


def _iter_file_blocks(reader, samples_per_read: int):
    """Yield [nchan, n] LOW-frequency-first blocks from a filterbank /
    PSRFITS / multi-file (fbobs) reader — the .mask channel convention
    (PRESTO reorders every band ascending on read, so mask channel 0 is
    always the lowest frequency regardless of on-disk order;
    io/rfimask.py docstring). ``get_samples`` (filterbank) and
    ``get_sample_interval`` (fbobs) return on-disk order, flipped here
    when the band is descending; the ``get_spectra`` fallback (PSRFITS)
    delivers high-frequency-first Spectra, always flipped."""
    total = int(getattr(reader, "nspec", None)
                or reader.number_of_samples)
    get_samples = getattr(reader, "get_samples", None)
    get_interval = getattr(reader, "get_sample_interval", None)
    raw = get_samples is not None or get_interval is not None
    if raw:
        f = np.asarray(reader.frequencies, dtype=float)  # on-disk order
        flip = len(f) > 1 and f[0] > f[-1]
    else:
        flip = True
    pos = 0
    while pos < total:
        n = min(samples_per_read, total - pos)
        if get_samples is not None:
            d = get_samples(pos, n).T
        elif get_interval is not None:
            d = get_interval(pos, pos + n).T
        else:
            d = np.asarray(reader.get_spectra(pos, n).data)
        yield d[::-1] if flip else d
        pos += n


def rfifind(
    source,
    *,
    time: float = 1.0,
    dt: Optional[float] = None,
    time_sigma: float = 10.0,
    freq_sigma: float = 4.0,
    chanfrac: float = 0.7,
    intfrac: float = 0.3,
    zap_chans: Sequence[int] = (),
    zap_ints: Sequence[int] = (),
    outbase: Optional[str] = None,
    lofreq: float = 0.0,
    df: float = 0.0,
    mjd: float = 0.0,
    ints_per_read: int = 16,
    hifreq_first: bool = True,
):
    """End-to-end mask generation.

    ``source`` is a reader (FilterbankFile / PsrfitsFile: dt, nspec/fch1
    discovered) or a raw [nchan, T] array (then ``dt`` is required and
    lofreq/df/mjd may be given; rows are taken high-frequency-first — the
    framework's Spectra convention — unless ``hifreq_first=False``).
    Returns (RfiStats, flags, maskfn-or-None), all in the .mask channel
    convention (channel 0 = lowest frequency); pass ``outbase`` to write
    ``{outbase}_rfifind.mask`` (+ ``.stats.npz``).

    The interval length is ``time`` seconds rounded to whole samples; a
    trailing partial interval shorter than half an interval is dropped
    (it has too few samples for stable statistics), otherwise it is
    padded by repeating its last sample into a full interval.
    """
    if isinstance(source, np.ndarray) or hasattr(source, "ndim"):
        if dt is None:
            raise ValueError("dt is required for array input")
        data = np.asarray(source)
        if hifreq_first:
            data = data[::-1]
        nchan = data.shape[0]
        blocks = [data]
    else:
        dt = float(getattr(source, "dt", None) or source.tsamp)
        nchan = int(getattr(source, "nchans", None)
                    or getattr(source, "nchan"))
        f = np.asarray(source.frequencies, dtype=float)
        lofreq = float(f.min())
        df = float(abs(f[1] - f[0])) if len(f) > 1 else 0.0
        mjd = 0.0
        try:
            mjd = float(source.tstart)  # SIGPROC header
        except (AttributeError, TypeError):
            pass
        if not mjd and hasattr(source, "specinfo"):  # PSRFITS
            try:
                mjd = float(np.atleast_1d(source.specinfo.start_MJD)[0])
            except (AttributeError, TypeError, IndexError):
                pass
        if not mjd and hasattr(source, "startmjds"):  # fbobs multi-file
            mjd = float(np.atleast_1d(source.startmjds)[0])
        blocks = None

    pts = max(int(round(time / dt)), 2)
    means, stds, maxpows = [], [], []
    carry = np.zeros((nchan, 0), dtype=np.float32)

    def consume(chunk, final=False):
        nonlocal carry
        buf = np.concatenate([carry, np.asarray(chunk, np.float32)], axis=1)
        nint = buf.shape[1] // pts
        if final:
            tail = buf.shape[1] - nint * pts
            if tail >= pts // 2:
                pad = np.repeat(buf[:, -1:], pts - tail, axis=1)
                buf = np.concatenate([buf, pad], axis=1)
                nint += 1
        if nint:
            telemetry.counter("rfifind.intervals", int(nint))
            # one batched pull per block (3 tunnel roundtrips otherwise)
            with telemetry.span("rfifind_block_stats", nint=int(nint)):
                m, s, p = transfer.pull_host(
                    *block_stats(buf[:, : nint * pts], pts))
            means.append(m)
            stds.append(s)
            maxpows.append(p)
        carry = buf[:, nint * pts:]

    if blocks is not None:
        for b in blocks:
            consume(b)
    else:
        for b in _iter_file_blocks(source, pts * ints_per_read):
            consume(b)
    consume(np.zeros((nchan, 0), np.float32), final=True)

    if not means:
        raise ValueError("no complete intervals: data shorter than time/2")
    stats = RfiStats(
        mean=np.concatenate(means), std=np.concatenate(stds),
        maxpow=np.concatenate(maxpows), ptsperint=pts, dtint=pts * dt,
        lofreq=lofreq, df=df, mjd=mjd,
    )
    flags = clip_stats(stats, time_sigma=time_sigma, freq_sigma=freq_sigma)
    zc, zi, per_int = mask_products(flags, chanfrac=chanfrac, intfrac=intfrac,
                                    extra_zap_chans=zap_chans,
                                    extra_zap_ints=zap_ints)
    # effective mask coverage (union of whole-channel, whole-interval and
    # per-interval zaps, via the reader's own table builder). A BRIGHT
    # PULSAR trips the Fourier max-power detector in every (interval,
    # channel) exactly like periodic RFI would — a known failure mode of
    # this class of detector (PRESTO's rfifind shares it); masking most
    # of the band deletes the signal the downstream search is looking
    # for, so shout.
    from pypulsar_tpu.io.rfimask import build_zap_table

    coverage = float(build_zap_table(stats.nint, stats.nchan, zc, zi,
                                     per_int).mean())
    stats.mask_coverage = coverage
    if coverage > 0.5:
        warnings.warn(
            f"mask covers {coverage * 100:.0f}% of the data — either RFI "
            f"is pervasive or a bright periodic source is being flagged "
            f"as interference; consider raising freq_sigma/time_sigma "
            f"or zapping known-bad channels explicitly", stacklevel=2)
    maskfn = None
    if outbase is not None:
        from pypulsar_tpu.io.rfimask import write_mask

        maskfn = write_mask(
            outbase + "_rfifind.mask", time_sigma=time_sigma,
            freq_sigma=freq_sigma, mjd=stats.mjd, dtint=stats.dtint,
            lofreq=stats.lofreq, df=stats.df, nchan=stats.nchan,
            nint=stats.nint, ptsperint=pts, zap_chans=zc, zap_ints=zi,
            zap_chans_per_int=per_int,
        )
        stats.save(outbase + "_rfifind.stats.npz")
    return stats, flags, maskfn
