"""NumPy golden twins of the JAX kernels.

These mirror the REFERENCE semantics (reference formats/spectra.py,
bin/zero_dm_filter.py) in float64 NumPy, serving as the bit-level spec for
parity tests (SURVEY.md §4 strategy 1). They are intentionally written in the
reference's own style (per-channel loops) so behavioral equivalence is easy to
audit, and are never used on the hot path.

Known reference defects (SURVEY.md §2.6) are FIXED here the same way they are
in the JAX kernels, so twin == kernel by construction:
- constructor dm discard (spectra.py:37): in_dm honored;
- trim(bins<0) slice bug (spectra.py:324-327): documented intent implemented.
"""

from __future__ import annotations

import numpy as np
import scipy.signal

from pypulsar_tpu.core.psrmath import delay_from_DM, rotate


def bin_delays(dm, freqs, dt, ref_freq=None):
    if ref_freq is None:
        ref_freq = np.max(freqs)
    rel = delay_from_DM(dm, np.asarray(freqs, dtype=np.float64)) - delay_from_DM(dm, ref_freq)
    return np.round(rel / dt).astype(np.int64)


def shift_channels(data, bins, padval=0):
    data = np.array(data, dtype=np.float64)
    C, T = data.shape
    for ii in range(C):
        chan = data[ii]
        chan[:] = rotate(chan, bins[ii])
        if padval != "rotate":
            if padval == "mean":
                pad = np.mean(chan)
            elif padval == "median":
                pad = np.median(chan)
            else:
                pad = padval
            if bins[ii] > 0:
                chan[-bins[ii]:] = pad
            elif bins[ii] < 0:
                chan[: -bins[ii]] = pad
    return data


def dedisperse(data, freqs, dt, dm, in_dm=0.0, padval=0):
    bins = bin_delays(dm - in_dm, freqs, dt)
    return shift_channels(data, bins, padval)


def subband(data, freqs, dt, nsub, subdm=None, in_dm=0.0, padval=0):
    data = np.array(data, dtype=np.float64)
    C, T = data.shape
    assert C % nsub == 0
    per = C // nsub
    hif = np.asarray(freqs)[np.arange(nsub) * per]
    lof = np.asarray(freqs)[(1 + np.arange(nsub)) * per - 1]
    ctr = 0.5 * (hif + lof)
    if subdm is not None:
        ref = delay_from_DM(subdm - in_dm, hif)
        delays = delay_from_DM(subdm - in_dm, np.asarray(freqs, dtype=np.float64))
        rel = delays - np.repeat(ref, per)
        bins = np.round(rel / dt).astype(np.int64)
        data = shift_channels(data, bins, padval)
    out = np.array([np.sum(sub, axis=0) for sub in np.vsplit(data, nsub)])
    return out, ctr


def downsample(data, factor):
    if factor <= 1:
        return np.array(data, dtype=np.float64)
    data = np.asarray(data, dtype=np.float64)
    C, T = data.shape
    T2 = T // factor
    data = data[:, : T2 * factor]
    return np.array(
        np.column_stack([np.sum(s, axis=1) for s in np.hsplit(data, T2)])
    )


def smooth(data, width, padval=0):
    data = np.array(data, dtype=np.float64)
    if width <= 1:
        return data
    C, T = data.shape
    kernel = np.ones(width, dtype="float32") / np.sqrt(width)
    for ii in range(C):
        chan = data[ii]
        if padval == "wrap":
            tosmooth = np.concatenate([chan[-width:], chan, chan[:width]])
        elif padval == "mean":
            tosmooth = np.ones(T + width * 2) * np.mean(chan)
            tosmooth[width:-width] = chan
        elif padval == "median":
            tosmooth = np.ones(T + width * 2) * np.median(chan)
            tosmooth[width:-width] = chan
        else:
            tosmooth = np.ones(T + width * 2) * padval
            tosmooth[width:-width] = chan
        smoothed = scipy.signal.convolve(tosmooth, kernel, "same")
        chan[:] = smoothed[width:-width]
    return data


def scaled(data, indep=False):
    data = np.array(data, dtype=np.float64)
    if not indep:
        std = data.std()
    for ii in range(data.shape[0]):
        chan = data[ii]
        median = np.median(chan)
        if indep:
            std = chan.std()
        chan[:] = (chan - median) / std
    return data


def scaled2(data, indep=False):
    data = np.array(data, dtype=np.float64)
    if not indep:
        mx = data.max()
    for ii in range(data.shape[0]):
        chan = data[ii]
        mn = chan.min()
        if indep:
            mx = chan.max()
        chan[:] = (chan - mn) / mx
    return data


def masked(data, mask, maskval="median-mid80"):
    data = np.array(data, dtype=np.float64)
    C, T = data.shape
    maskvals = np.ones(C)
    for ii in range(C):
        chan = data[ii]
        if maskval == "mean":
            maskvals[ii] = np.mean(chan)
        elif maskval == "median":
            maskvals[ii] = np.median(chan)
        elif maskval == "median-mid80":
            n = int(np.round(0.1 * T))
            if n == 0:
                maskvals[ii] = np.median(chan)
            else:
                maskvals[ii] = np.median(np.sort(chan)[n:-n])
        else:
            maskvals[ii] = maskval
    tmp = np.ones_like(data) * maskvals[:, np.newaxis]
    return np.where(mask, tmp, data)


def zero_dm(data):
    data = np.asarray(data, dtype=np.float64)
    return data - data.mean(axis=0, keepdims=True)


def trim(data, bins):
    data = np.asarray(data, dtype=np.float64)
    if bins == 0:
        return data
    if bins > 0:
        return data[:, :-bins]
    return data[:, -bins:]


def dedispersed_timeseries(data, bins):
    return shift_channels(data, bins, padval="rotate").sum(axis=0)


def boxcar_snr(ts, widths):
    ts = np.asarray(ts, dtype=np.float64)
    med = np.median(ts)
    std = np.std(ts)
    norm = (ts - med) / (std if std != 0 else 1.0)
    cs = np.concatenate([[0.0], np.cumsum(norm)])
    snrs, idxs = [], []
    for w in widths:
        sums = (cs[w:] - cs[:-w]) / np.sqrt(float(w))
        snrs.append(sums.max())
        idxs.append(int(sums.argmax()))
    return np.array(snrs), np.array(idxs)
