"""Pallas TPU kernels for the detection hot loop.

``boxcar_stats`` is the per-trial matched-filter statistics stage of the
DM sweep (parallel/sweep.py): given dedispersed time series ts[D, T], for
every trial compute the payload sum and sum-of-squares plus, for each
boxcar width w, the maximum (and argmax) of the w-sample running sum over
windows starting in the payload.

The XLA formulation materializes a [D, T] window-sum array per width in
HBM (W passes over HBM).  The Pallas kernel streams a block of trials
through VMEM once: the cumulative sum is formed in VMEM scratch and every
width's windowed difference, max, and argmax are reduced in-register —
HBM traffic drops from (W+1) x D x T reads to a single one.

Falls back transparently to the lax implementation off-TPU (and runs in
interpret mode inside CPU tests).
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

D_BLOCK = 8  # float32 sublane count: one tile of trials per grid step


def _shift_left(x, k: int):
    """x[:, t] -> x[:, t+k], zero-filled at the tail (static slice)."""
    if k == 0:
        return x
    return jnp.concatenate(
        [x[:, k:], jnp.zeros((x.shape[0], k), x.dtype)], axis=1)


def _boxcar_kernel(ts_ref, halo_ref, s_ref, ss_ref, mb_ref, ab_ref,
                   *, widths: Tuple[int, ...], stat_len: int,
                   t_block: int):
    """One [D_BLOCK, t_block] time tile (plus max-width halo): partial
    payload stats and per-width windowed max, accumulated across the time
    grid axis (same output block revisited per j; init at j == 0).

    Window sums come from a dyadic doubling table instead of a cumsum
    (``cumsum`` has no Pallas TPU lowering, and the doubling scheme also
    avoids the cumsum's cancellation error at large T): dy[k][t] =
    sum ts[t : t+2^k), built with log2(maxw) shifted adds; an arbitrary
    width is the sum of its binary components at increasing offsets.
    """
    from jax.experimental import pallas as pl

    j = pl.program_id(1)
    maxw = max(widths)
    data = jnp.concatenate([ts_ref[:, :], halo_ref[:, :]], axis=1)

    # window starts (and payload samples) valid within this tile
    t0 = j * t_block
    local_idx = jax.lax.broadcasted_iota(jnp.int32, (D_BLOCK, t_block), 1)
    valid = (t0 + local_idx) < stat_len

    payload = jnp.where(valid, data[:, :t_block], 0.0)
    part_s = jnp.sum(payload, axis=-1)
    part_ss = jnp.sum(payload * payload, axis=-1)

    dyadic = [data]
    k = 0
    while (1 << (k + 1)) <= maxw:
        step = 1 << k
        dyadic.append(dyadic[k] + _shift_left(dyadic[k], step))
        k += 1

    neg = jnp.asarray(-jnp.inf, data.dtype)
    local_mb = []
    local_ab = []
    for w in widths:
        box = None
        off = 0
        for bit in range(int(w).bit_length()):
            if w & (1 << bit):
                part = _shift_left(dyadic[bit], off)
                box = part if box is None else box + part
                off += 1 << bit
        box = jnp.where(valid, box[:, :t_block], neg)
        local_mb.append(jnp.max(box, axis=-1))
        local_ab.append(t0 + jnp.argmax(box, axis=-1).astype(jnp.int32))
    lmb = jnp.stack(local_mb, axis=-1)
    lab = jnp.stack(local_ab, axis=-1)

    @pl.when(j == 0)
    def _init():
        s_ref[:, 0] = part_s
        ss_ref[:, 0] = part_ss
        mb_ref[:, :] = lmb
        ab_ref[:, :] = lab

    @pl.when(j > 0)
    def _accumulate():
        s_ref[:, 0] += part_s
        ss_ref[:, 0] += part_ss
        better = lmb > mb_ref[:, :]
        mb_ref[:, :] = jnp.where(better, lmb, mb_ref[:, :])
        ab_ref[:, :] = jnp.where(better, lab, ab_ref[:, :])


def _pallas_boxcar_stats(ts, widths: Tuple[int, ...], stat_len: int,
                         interpret: bool = False, t_block: int = 8192):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    D, T = ts.shape
    W = len(widths)
    maxw = int(max(widths))
    # TPU lane constraint: the halo block's last dim must be a multiple
    # of 128; time blocks must be a multiple of the halo width so its
    # block index is integral
    halo = -(-maxw // 128) * 128
    t_block = max(halo, (t_block // halo) * halo)
    n_t = -(-stat_len // t_block)
    pad_d = (-D) % D_BLOCK
    # pad the time axis so every tile's halo read stays in bounds.  With
    # the default widths (maxw=32 < halo=128) this fires on every call;
    # the copy is of the [D, T] detection series only (a few percent of
    # the dedispersion stage's traffic), the price of a lane-aligned
    # halo block.
    pad_t = max(n_t * t_block + halo - T, 0)
    if pad_d or pad_t:
        ts = jnp.pad(ts, ((0, pad_d), (0, pad_t)))
    Dp = D + pad_d

    kernel = partial(_boxcar_kernel, widths=tuple(int(w) for w in widths),
                     stat_len=stat_len, t_block=t_block)
    s, ss, mb, ab = pl.pallas_call(
        kernel,
        grid=(Dp // D_BLOCK, n_t),
        in_specs=[
            pl.BlockSpec((D_BLOCK, t_block), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
            # halo: the samples after the tile (offset in halo units)
            pl.BlockSpec((D_BLOCK, halo),
                         lambda i, j, _tb=t_block, _h=halo:
                         (i, (j + 1) * _tb // _h),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((D_BLOCK, 1), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((D_BLOCK, 1), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((D_BLOCK, W), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((D_BLOCK, W), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Dp, 1), ts.dtype),
            jax.ShapeDtypeStruct((Dp, 1), ts.dtype),
            jax.ShapeDtypeStruct((Dp, W), ts.dtype),
            jax.ShapeDtypeStruct((Dp, W), jnp.int32),
        ],
        interpret=interpret,
    )(ts, ts)
    return s[:D, 0], ss[:D, 0], mb[:D], ab[:D]


def _lax_boxcar_stats(ts, widths: Tuple[int, ...], stat_len: int):
    """Reference lax formulation (same math as parallel/sweep.py)."""
    payload = ts[:, :stat_len]
    s = payload.sum(axis=-1)
    ss = (payload * payload).sum(axis=-1)
    cs = jnp.concatenate(
        [jnp.zeros((ts.shape[0], 1), ts.dtype),
         jnp.cumsum(ts, axis=-1)], axis=-1)
    maxs, args = [], []
    for w in widths:
        box = cs[:, w:w + stat_len] - cs[:, :stat_len]
        maxs.append(box.max(axis=-1))
        args.append(box.argmax(axis=-1).astype(jnp.int32))
    return s, ss, jnp.stack(maxs, -1), jnp.stack(args, -1)


def _on_tpu() -> bool:
    try:
        # lazy import: parallel.sweep imports this module at load time,
        # so a module-level ops -> parallel.mesh import would cycle;
        # resolving through the lease registry (PL002) keeps the
        # backend probe honest under a gang lease
        from pypulsar_tpu.parallel.mesh import lease_devices

        return lease_devices()[0].platform == "tpu"
    except Exception:
        return False


@partial(jax.jit, static_argnames=("widths", "stat_len", "backend"))
def boxcar_stats(ts, widths: Tuple[int, ...], stat_len: int,
                 backend: str = "auto"):
    """(sum[D], sumsq[D], maxbox[D, W], argbox[D, W]) over ts[D, T] with
    windows starting in the first ``stat_len`` samples.

    ``backend``: 'pallas' (TPU kernel), 'lax', 'interpret' (pallas
    interpreter, for tests), or 'auto' (pallas on TPU, lax elsewhere).
    """
    ts = jnp.asarray(ts)
    if ts.shape[1] < stat_len + max(widths):
        raise ValueError(
            f"time axis {ts.shape[1]} shorter than stat_len+max(width) "
            f"= {stat_len + max(widths)}")
    widths = tuple(int(w) for w in widths)
    if backend == "auto":
        backend = "pallas" if _on_tpu() else "lax"
    if backend == "pallas":
        return _pallas_boxcar_stats(ts, widths, stat_len)
    if backend == "interpret":
        return _pallas_boxcar_stats(ts, widths, stat_len, interpret=True)
    return _lax_boxcar_stats(ts, widths, stat_len)
