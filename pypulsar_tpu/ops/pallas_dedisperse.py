"""Pallas TPU kernel for the dedispersion hot loop: shifted gather-sum.

The sweep engine's inner operation (both subband stages) is

    out[o, t] = sum_k  data[rows[o, k],  shifts[o, k] + t]

i.e. sum K shifted rows of a [R, L] array into each of O outputs.  The
XLA formulation (vmapped ``lax.dynamic_slice``) lowers to a generic
gather measured ~26 GB/s effective on v5e (3% of the HBM roofline;
BENCHNOTES.md round-3 A/B — the Fourier phase-multiply engine has since
superseded both).  This kernel instead streams each row segment HBM->VMEM with
explicit double-buffered DMA whose offsets come from scalar-prefetched
shift tables, and accumulates in VMEM — the access pattern the hardware
DMA engines are built for.

``shifted_gather_sum`` currently defaults to the lax formulation
everywhere: the Pallas path (``backend='pallas'``) is implemented and
validated in interpret mode, but the AOT TPU compiler available in this
environment crashes on any DMA/load with a *dynamic* offset (plain
static-offset DMA kernels compile fine — see ops/pallas_kernels.py), so
the kernel cannot yet be enabled by default.  Re-evaluate with
``backend='pallas'`` on a toolchain where dynamic-offset DMA lowers.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from pypulsar_tpu.ops.pallas_kernels import _on_tpu  # noqa: F401 (shared)

T_BLOCK = 2048  # lanes per grid step (multiple of 128)


def _gather_sum_kernel(rows_ref, shifts_ref, data_ref, out_ref,
                       *, K: int, t_block: int):
    """One (o, j) tile: out[o, j*t_block : (j+1)*t_block] accumulated over
    the K shifted source rows, with double-buffered row DMA."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    o = pl.program_id(0)
    j = pl.program_id(1)
    t0 = j * t_block

    def body(scratch, acc, sem):
        def get_dma(slot, k):
            row = rows_ref[o, k]
            start = shifts_ref[o, k] + t0
            return pltpu.make_async_copy(
                data_ref.at[row, pl.ds(start, t_block)],
                scratch.at[slot],
                sem.at[slot],
            )

        get_dma(0, 0).start()
        acc[:] = jnp.zeros((t_block,), out_ref.dtype)

        def loop_body(k, _):
            slot = k % 2

            @pl.when(k + 1 < K)
            def _start_next():
                get_dma((k + 1) % 2, k + 1).start()

            get_dma(slot, k).wait()
            acc[:] += scratch[slot]

        jax.lax.fori_loop(0, K, loop_body, None)
        out_ref[:] = acc[:]

    pl.run_scoped(
        body,
        scratch=pltpu.VMEM((2, t_block), out_ref.dtype),
        acc=pltpu.VMEM((t_block,), out_ref.dtype),
        sem=pltpu.SemaphoreType.DMA((2,)),
    )


def _pallas_gather_sum(data, rows, shifts, out_len: int,
                       interpret: bool = False, t_block: int = T_BLOCK):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    O, K = rows.shape
    # lane alignment: tile width must be a multiple of 128
    t_block = min(t_block, max(128, -(-out_len // 128) * 128))
    n_t = -(-out_len // t_block)
    padded_len = n_t * t_block
    # the last tile reads up to shift + padded_len <= shift + out_len +
    # t_block; the caller guarantees shift + out_len <= L (same contract
    # as the lax path), so t_block zeros of tail padding keep every DMA
    # in bounds
    data = jnp.pad(data, ((0, 0), (0, t_block)))
    # flat 1-D output (block = one tile) sidesteps the (8, 128) 2-D block
    # alignment constraint; row o occupies [o*padded_len, (o+1)*padded_len)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(O, n_t),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=pl.BlockSpec((t_block,),
                               lambda o, j, *_, _nt=n_t: (o * _nt + j,),
                               memory_space=pltpu.VMEM),
    )
    out = pl.pallas_call(
        partial(_gather_sum_kernel, K=K, t_block=t_block),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((O * padded_len,), data.dtype),
        interpret=interpret,
    )(rows, shifts, data)
    return out.reshape(O, padded_len)[:, :out_len]


def _lax_gather_sum(data, rows, shifts, out_len: int):
    """Reference formulation: vmapped dynamic-slice gather + sum."""
    def one_out(r, s):
        picked = data[r]  # [K, L]
        sliced = jax.vmap(
            lambda row, st: jax.lax.dynamic_slice(row, (st,), (out_len,))
        )(picked, s)
        return sliced.sum(axis=0)

    return jax.vmap(one_out)(rows, shifts)


@partial(jax.jit, static_argnames=("out_len", "backend"))
def shifted_gather_sum(data, rows, shifts, out_len: int,
                       backend: str = "auto"):
    """out[o, t] = sum_k data[rows[o, k], shifts[o, k] + t] for
    t in [0, out_len).

    ``data`` is [R, L] float32; ``rows``/``shifts`` are [O, K] int32 with
    every window ``shifts + out_len`` (after internal padding to the tile
    size) within L.  ``backend``: 'pallas', 'lax', 'interpret', or 'auto'
    (pallas on TPU).
    """
    data = jnp.asarray(data)
    rows = jnp.asarray(rows, jnp.int32)
    shifts = jnp.asarray(shifts, jnp.int32)
    if backend == "auto":
        # dynamic-offset DMA does not lower in this environment's AOT
        # TPU compiler (see module docstring); opt in explicitly
        backend = "lax"
    if backend == "pallas":
        return _pallas_gather_sum(data, rows, shifts, out_len)
    if backend == "interpret":
        return _pallas_gather_sum(data, rows, shifts, out_len,
                                  interpret=True)
    return _lax_gather_sum(data, rows, shifts, out_len)
