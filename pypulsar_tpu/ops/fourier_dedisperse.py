"""Fourier-domain two-stage dedispersion: the TPU fast path of the sweep.

Why: the time-domain formulation of the sweep's hot loop — per-row
``dynamic_slice`` gathers (parallel/sweep.py ``_slice_rows``) — lowers to a
generic XLA gather that measured **26 GB/s effective on v5e** (3% of the
819 GB/s HBM roofline; see BENCHNOTES.md for the recorded A/B), and the
Pallas dynamic-offset-DMA alternative does not compile on this toolchain
(ops/pallas_dedisperse.py). This module removes the gather entirely: a
circular shift by ``s`` bins is multiplication by ``exp(2i*pi*k*s/n)`` in
the Fourier domain, so the whole two-stage shift-and-sum becomes

    X = rfft(chunk)                                    # once per chunk
    stage 1 (per group):  Xsub[s] = sum_{c in s} X[c] * W^(k*s1[g,c])
    stage 2 (per trial):  Xts    = sum_s  Xsub[s] * W^(k*s2[d,s])
    ts = irfft(Xts)[:, :out_len]

— batched power-of-two FFTs plus *elementwise multiply-reduce* streams,
the access pattern XLA fuses to full bandwidth on TPU. The default
``phase_mode='factored'`` further factors the frequency-bin axis
(k = M*hi + lo) so the per-shift phase costs ~2*sqrt(F) transcendentals
instead of F — the round-3 profile showed the stages were
phase-generation-bound at ~92G cos-sin/s, and this lifted the measured
chunk time from 323 ms to 146 ms on v5e (BENCHNOTES.md round-4 A/B). Phases compose
additively, so the total integer shift per channel is EXACTLY the same
``s1 + s2`` the time-domain path applies: results agree to FFT f32
rounding, inside the sweep's SNR parity contract of <=2e-6 relative SNR
(measured worst case 5e-7; README "Golden parity"; enforced in
tests/test_sweep.py::test_fourier_engine_snr_tolerance).

Exactness of the phase table: with ``n`` a power of two, the index
``(k * s) mod n`` needs only the low ``log2(n)`` bits of the product, which
int32 wraparound multiplication preserves — no int64, no float64, no
accumulated phase error at large ``k*s``.

Zero-padding to ``n >= chunk_len + max_total_shift`` guarantees circular
shifts never wrap data into the valid window (the pad region is what wraps,
and it is zero — matching the time-domain path's zero end-padding).

Reference treatment: nonexistent (the reference dedisperses with per-channel
Python rolls, formats/spectra.py:54-94, one trial at a time on one core).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from pypulsar_tpu.ops.pallas_kernels import boxcar_stats

__all__ = ["sweep_chunk_fourier", "sweep_chunk_spectra",
           "fourier_chunk_len"]


def fourier_chunk_len(min_len: int) -> int:
    """Smallest power-of-two FFT length >= min_len. TPU XLA lowers only
    power-of-two FFTs efficiently (other sizes fall back to a dense DFT
    matmul that allocates O(L^2) — observed 77 GB for L=139194)."""
    n = 1
    while n < min_len:
        n <<= 1
    return n


def _phase(shifts, k, n_fft: int):
    """exp(2i*pi*k*shifts/n) for integer shifts[...] and bins k[F]:
    shift-LEFT by s in time is multiplication by W^(+k*s) in frequency.
    Index math wraps mod n via int32 overflow (exact for power-of-two n)."""
    idx = (k * shifts[..., None]) & jnp.int32(n_fft - 1)
    ang = (2.0 * jnp.pi / n_fft) * idx.astype(jnp.float32)
    return jax.lax.complex(jnp.cos(ang), jnp.sin(ang))


def _phase_table(max_shift: int, k, n_fft: int, stride: int = 1):
    """[max_shift//stride + 1, F] rows of W^(k * stride * j) — the phase
    of every possible (strided) integer shift, built once per dispatch so
    the per-trial phase becomes a row gather (+ one complex multiply for
    a hi*lo factorization) instead of per-element cos/sin. The v5e probe
    measured the gathered stage-2 ~2x the transcendental formulation
    (BENCHNOTES.md round-3 component table)."""
    j = jnp.arange(max_shift // stride + 1, dtype=jnp.int32) * stride
    return _phase(j, k, n_fft)


_LUT_LO = 64  # stage-2 shifts factor as s = 64*hi + lo; tables stay ~100 MB


def _fact_split(F: int) -> int:
    """Power-of-two M minimizing ceil(F/M) + M — the per-shift
    transcendental count of the bin-axis factorization below."""
    best, best_cost = 1, F + 1
    m = 1
    while m <= F:
        cost = -(-F // m) + m
        if cost < best_cost:
            best, best_cost = m, cost
        m <<= 1
    return best




def sweep_chunk_fourier_impl(
    data,
    stage1_bins,
    stage2_bins,
    nsub: int,
    out_len: int,
    widths: Tuple[int, ...],
    stat_len: int,
    n_fft: int,
    boxcar_backend: str = "auto",
    phase_mode: str = "factored",
    max_shift1: int = 0,
    max_shift2: int = 0,
):
    """Fourier-path equivalent of parallel.sweep._sweep_chunk_impl.

    data[C, L] (L <= n_fft; n_fft >= out_len + max total shift so shifts
    cannot wrap); stage1_bins[G, C]; stage2_bins[G, g, S].
    Returns per-trial (sum[D], sumsq[D], maxbox[D, W], argbox[D, W]) with
    window starts confined to the first ``stat_len`` samples.

    ``phase_mode``: 'factored' (default) factors the BIN axis
    (k = M*hi + lo => W^(s*k) = W^((s*M)*hi) * W^(s*lo)) so each shift
    costs ~2*sqrt(F) cos/sin pairs instead of F, applied as two rank-3
    broadcast complex multiplies over the spectrum viewed as [C, Fh, M]
    — gather-free, no F-length phase row ever materialized. 'direct'
    computes cos/sin per element; 'lut' gathers per-shift phase rows
    from tables built once per dispatch, stage 2 factoring
    ``s = 64*hi + lo`` into two table rows and one complex multiply.
    All use the same exact int32-wraparound index math; factored/lut
    differ from direct by one extra f32 complex multiply (~3e-7
    relative), inside the sweep's SNR parity budget. Measured on v5e
    (round-4 A/B, bench geometry, 1024-trial chunk): factored 146 ms
    vs direct 323 ms vs lut 646 ms — the round-3 "transcendental
    floor" was real (the stages were phase-generation-bound) and the
    bin-axis factorization removes it; the earlier LUT attempt lost
    because it factored the SHIFT axis and paid per-element gathers.
    'lut' needs the static bounds ``max_shift1``/``max_shift2``
    (<=0 falls back to 'direct').
    """
    C, L = data.shape
    G, g, S = stage2_bins.shape
    per = C // nsub
    X = jnp.fft.rfft(data, n=n_fft, axis=1)  # [C, F]
    F = X.shape[1]
    k = jnp.arange(F, dtype=jnp.int32)
    use_lut = phase_mode == "lut" and max_shift1 >= 0 and max_shift2 >= 0 \
        and (max_shift1 or max_shift2)
    if use_lut:
        t1 = _phase_table(max_shift1, k, n_fft)  # [max1+1, F]
        t_hi = _phase_table(max_shift2, k, n_fft, stride=_LUT_LO)
        t_lo = _phase_table(min(_LUT_LO - 1, max_shift2), k, n_fft)

    if phase_mode == "factored":
        # Bin-axis factorization k = M*hi + lo: view the spectrum as
        # [C, Fh, M] (zero-padded to Fh*M bins) and apply the phase as two
        # rank-3 broadcast multiplies — hi along axis 1, lo along axis 2 —
        # so no F-length phase row is ever materialized and each shift
        # costs only Fh + M ~ 2*sqrt(F) cos/sin pairs.
        M = _fact_split(F)
        Fh = -(-F // M)
        k_hi = jnp.arange(Fh, dtype=jnp.int32)
        k_lo = jnp.arange(M, dtype=jnp.int32)
        Xp = jnp.pad(X, ((0, 0), (0, Fh * M - F))).reshape(C, Fh, M)

        def per_group_fact(carry, xs):
            s1, s2 = xs  # [C], [g, S]
            hi1 = _phase(s1 * jnp.int32(M), k_hi, n_fft)  # [C, Fh]
            lo1 = _phase(s1, k_lo, n_fft)                 # [C, M]
            xsub = (Xp * hi1[:, :, None] * lo1[:, None, :]) \
                .reshape(nsub, per, Fh, M).sum(axis=1)     # [S, Fh, M]
            hi2 = _phase(s2 * jnp.int32(M), k_hi, n_fft)  # [g, S, Fh]
            lo2 = _phase(s2, k_lo, n_fft)                 # [g, S, M]
            xts = (xsub[None] * hi2[..., None] * lo2[..., None, :]) \
                .sum(axis=1)                               # [g, Fh, M]
            xts = xts.reshape(-1, Fh * M)[:, :F]
            ts = jnp.fft.irfft(xts, n=n_fft, axis=1)[:, :out_len]
            s, ss, mb_g, ab_g = boxcar_stats(ts, widths, stat_len,
                                             backend=boxcar_backend)
            return carry, (s, ss, mb_g, ab_g)

    def per_group(carry, xs):
        s1, s2 = xs  # [C], [g, S]
        if use_lut:
            ph1 = t1[s1]
            ph2 = t_hi[s2 // _LUT_LO] * t_lo[s2 % _LUT_LO]
        else:
            ph1 = _phase(s1, k, n_fft)
            ph2 = _phase(s2, k, n_fft)
        xsub = (X * ph1).reshape(nsub, per, F).sum(axis=1)
        xts = (xsub[None, :, :] * ph2).sum(axis=1)  # [g, F]
        ts = jnp.fft.irfft(xts, n=n_fft, axis=1)[:, :out_len]
        s, ss, mb_g, ab_g = boxcar_stats(ts, widths, stat_len,
                                         backend=boxcar_backend)
        return carry, (s, ss, mb_g, ab_g)

    body = per_group_fact if phase_mode == "factored" else per_group
    _, (s, ss, mb, ab) = jax.lax.scan(body, 0, (stage1_bins, stage2_bins))
    D = G * g
    return (
        s.reshape(D),
        ss.reshape(D),
        mb.reshape(D, len(widths)),
        ab.reshape(D, len(widths)),
    )


sweep_chunk_fourier = jax.jit(
    sweep_chunk_fourier_impl,
    static_argnames=("nsub", "out_len", "widths", "stat_len", "n_fft",
                     "boxcar_backend", "phase_mode", "max_shift1",
                     "max_shift2"),
)


def dedisperse_series_fourier_impl(
    data,
    stage1_bins,
    stage2_bins,
    nsub: int,
    out_len: int,
    n_fft: int,
    phase_mode: str = "factored",
):
    """Two-stage subband dedispersed SERIES for every trial: the same
    phase math as :func:`sweep_chunk_fourier_impl` with the fused boxcar
    detection swapped for the raw [D, out_len] time series — the chunk
    kernel of the streamed .dat writer (cli sweep --write-dats on files
    too large for a device-resident Spectra; PRESTO-prepsubband
    semantics: subband dedispersion, not per-channel-exact)."""
    C, L = data.shape
    G, g, S = stage2_bins.shape
    per = C // nsub
    X = jnp.fft.rfft(data, n=n_fft, axis=1)  # [C, F]
    F = X.shape[1]
    k = jnp.arange(F, dtype=jnp.int32)

    if phase_mode == "factored":
        M = _fact_split(F)
        Fh = -(-F // M)
        k_hi = jnp.arange(Fh, dtype=jnp.int32)
        k_lo = jnp.arange(M, dtype=jnp.int32)
        Xp = jnp.pad(X, ((0, 0), (0, Fh * M - F))).reshape(C, Fh, M)

        def body(carry, xs):
            s1, s2 = xs
            hi1 = _phase(s1 * jnp.int32(M), k_hi, n_fft)
            lo1 = _phase(s1, k_lo, n_fft)
            xsub = (Xp * hi1[:, :, None] * lo1[:, None, :]) \
                .reshape(nsub, per, Fh, M).sum(axis=1)
            hi2 = _phase(s2 * jnp.int32(M), k_hi, n_fft)
            lo2 = _phase(s2, k_lo, n_fft)
            xts = (xsub[None] * hi2[..., None] * lo2[..., None, :]) \
                .sum(axis=1)
            xts = xts.reshape(-1, Fh * M)[:, :F]
            return carry, jnp.fft.irfft(xts, n=n_fft, axis=1)[:, :out_len]
    else:
        def body(carry, xs):
            s1, s2 = xs
            ph1 = _phase(s1, k, n_fft)
            ph2 = _phase(s2, k, n_fft)
            xsub = (X * ph1).reshape(nsub, per, F).sum(axis=1)
            xts = (xsub[None, :, :] * ph2).sum(axis=1)
            return carry, jnp.fft.irfft(xts, n=n_fft, axis=1)[:, :out_len]

    _, ts = jax.lax.scan(body, 0, (stage1_bins, stage2_bins))
    return ts.reshape(G * g, out_len)


dedisperse_series_fourier = jax.jit(
    dedisperse_series_fourier_impl,
    static_argnames=("nsub", "out_len", "n_fft", "phase_mode"),
)


def sweep_chunk_spectra_impl(
    data,
    stage1_bins,
    stage2_bins,
    nsub: int,
    n_fft: int,
    dec_stride: int,
    dec_len: int,
    mean_len: int,
    phase_mode: str = "factored",
):
    """Per-trial dedispersed SPECTRA, pre-irfft — the spectral-fusion
    kernel (round 15). Same two-stage phase math as
    :func:`dedisperse_series_fourier_impl` with the final irfft DELETED:
    the per-trial ``Xts`` is kept in the Fourier domain and DECIMATED
    onto the accel stage's T-point grid (``dec_stride = n_fft // T``,
    ``dec_len = T//2 + 1``, ``mean_len = T``; requires ``n_fft % T ==
    0`` and data support confined to ``[0, T)``). Returns ``(re, im)``
    float32 planes ``[D, dec_len]`` (complex never crosses the jit
    boundary, ops/transfer.py).

    Boundary semantics — read before trusting parity: decimating by
    ``n_fft/T`` in frequency is alias-folding the implied frame to
    period T in time, so the result is EXACTLY the spectrum of the
    **circularly** dedispersed series ``ts[u] = sum_c x_c[(u + s_c) mod
    T]`` — the Fourier-domain-dedispersion convention (PAPERS.md
    2110.03482 applies the chirp to the full-observation spectrum the
    same way). The framework's time-domain engines use PRESTO's
    zero-padded LINEAR shifts instead; the two agree everywhere except
    the final ``max_total_shift`` samples, where linear has partial
    sums (channels read past the data end into zeros) and circular
    wraps in each channel's first ``s_c`` samples. No phase trick can
    reconcile them: every channel's full T samples are present in any
    phase-shifted frame, and the fold must put the ``s_c`` head samples
    — which the linear window never reads — SOMEWHERE in the period.
    This was measured, not guessed (BENCHNOTES round 10): the candidate
    tables differ at toy scale, which is why parallel/specfuse.py ships
    this kernel as the opt-in ``decimate`` regime and defaults to the
    bit-exact stitched regime.

    ``mean_len`` (= T): per-channel means over the real samples are
    subtracted first, masked so the zero pad stays zero. Each channel's
    subtracted boxcar spans exactly one fold period, which aliases to a
    CONSTANT — spectrally a pure bin-0 term, exactly like
    ``prep_spectra_batch``'s series-mean subtraction (also a bin-0
    edit), and deredden overwrites bin 0 anyway. Numerically it keeps
    the f32 butterflies at fluctuation scale instead of the ~100x-sigma
    DC of 8-bit data.
    """
    C, L = data.shape
    G, g, S = stage2_bins.shape
    per = C // nsub
    col = jnp.arange(L, dtype=jnp.int32)
    live = (col < mean_len).astype(data.dtype)[None, :]
    mu = (data * live).sum(axis=1, keepdims=True) / jnp.float32(mean_len)
    data = data - mu * live
    X = jnp.fft.rfft(data, n=n_fft, axis=1)  # [C, F]
    F = X.shape[1]
    k = jnp.arange(F, dtype=jnp.int32)
    didx = jnp.arange(dec_len, dtype=jnp.int32) * jnp.int32(dec_stride)

    if phase_mode == "factored":
        M = _fact_split(F)
        Fh = -(-F // M)
        k_hi = jnp.arange(Fh, dtype=jnp.int32)
        k_lo = jnp.arange(M, dtype=jnp.int32)
        Xp = jnp.pad(X, ((0, 0), (0, Fh * M - F))).reshape(C, Fh, M)

        def body(carry, xs):
            s1, s2 = xs
            hi1 = _phase(s1 * jnp.int32(M), k_hi, n_fft)
            lo1 = _phase(s1, k_lo, n_fft)
            xsub = (Xp * hi1[:, :, None] * lo1[:, None, :]) \
                .reshape(nsub, per, Fh, M).sum(axis=1)
            hi2 = _phase(s2 * jnp.int32(M), k_hi, n_fft)
            lo2 = _phase(s2, k_lo, n_fft)
            xts = (xsub[None] * hi2[..., None] * lo2[..., None, :]) \
                .sum(axis=1)
            xts = jnp.take(xts.reshape(-1, Fh * M), didx, axis=1)
            return carry, (xts.real.astype(jnp.float32),
                           xts.imag.astype(jnp.float32))
    else:
        def body(carry, xs):
            s1, s2 = xs
            ph1 = _phase(s1, k, n_fft)
            ph2 = _phase(s2, k, n_fft)
            xsub = (X * ph1).reshape(nsub, per, F).sum(axis=1)
            xts = (xsub[None, :, :] * ph2).sum(axis=1)
            xts = jnp.take(xts, didx, axis=1)
            return carry, (xts.real.astype(jnp.float32),
                           xts.imag.astype(jnp.float32))

    _, (re, im) = jax.lax.scan(body, 0, (stage1_bins, stage2_bins))
    return re.reshape(G * g, dec_len), im.reshape(G * g, dec_len)


sweep_chunk_spectra = jax.jit(
    sweep_chunk_spectra_impl,
    static_argnames=("nsub", "n_fft", "dec_stride", "dec_len", "mean_len",
                     "phase_mode"),
)
