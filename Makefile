# Developer entry points. (The reference's Makefile only deleted .pyc
# files; these targets drive the real workflows.)

PY ?= python
CPU_ENV = JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8

.PHONY: test test-fourier test-faults test-fold test-obs test-survey test-corruption test-tune test-multihost test-race test-daemon test-broker test-candstore bench-broker bench-candplane lint dryrun smoke probe bench bench-quick bench-ab bench-accel bench-accel-pipeline bench-fold bench-obs bench-survey bench-multichip bench-multihost-fleet bench-specfuse bench-telemetry bench-tree bench-tune bench-compile native clean

# every device engine on the live TPU, one PASS/FAIL line each (~1 min)
smoke:
	$(PY) tools/tpu_smoke.py

# per-component kernel timings on the live TPU (BENCHNOTES tables)
probe:
	$(PY) tools/tpu_component_probe.py

test: lint test-obs test-candstore
	$(CPU_ENV) $(PY) -m pytest tests/ -q

# the static-analysis gate (docs/ARCHITECTURE.md "Static analysis"):
# psrlint's project-invariant rules PL001-PL018 (each locks in a bug
# class an earlier PR fixed by hand — PL011: raw PYPULSAR_TPU_* env
# reads outside the tune/knobs.py registry; PL012-PL016: the psrrace
# concurrency rules — lock-order cycles, blocking-under-lock, bare
# acquires, unguarded condition waits, orphanable threads; PL017:
# telemetry names consumed by tlmsum/bench/tests must match an emitter,
# and emitted events must have a consumer; PL018: raw jax.jit outside
# the round-22 compilation plane (compile/ + the ops leaf allowlist);
# baseline
# empty by policy), then the
# third-party ruff pass (pyproject [tool.ruff], crash-bug classes
# only) when the container ships ruff — the image this repo grows in
# does not, so the ruff leg degrades to a loud skip, never a pass
lint:
	$(PY) -m pypulsar_tpu.cli psrlint --baseline tools/lint_baseline.json
	@if $(PY) -c "import ruff" 2>/dev/null; then \
		$(PY) -m ruff check .; \
	else \
		echo "# ruff not installed: third-party pass skipped (psrlint gate ran)"; \
	fi

# the whole suite with the TPU-default engine forced (cross-engine check)
test-fourier:
	PYPULSAR_TPU_SWEEP_ENGINE=fourier $(CPU_ENV) $(PY) -m pytest tests/ -q

# the resilience suite: injected OOM / IO errors / kill+resume at every
# journal kill-point, candidate tables proven bit-identical to unfaulted
# runs (docs/ARCHITECTURE.md "Failure model & recovery") — plus the
# survey orchestrator's kill/resume/quarantine and fleet-health
# (watchdog, device-strike, admission) cases, and the seeded chaos
# fleet
test-faults: test-chaos test-corruption test-multihost test-race test-obs test-daemon test-broker test-candstore
	$(CPU_ENV) $(PY) -m pytest tests/test_resilience.py -q
	$(CPU_ENV) $(PY) -m pytest tests/test_survey.py -q -k "kill or resume or quarantine or retry or stall or deadline or evict or admission or chaos"

# the observability-plane suite (round 21): causal trace ids surviving
# kill+resume and cross-host adoption (one stitched trace, tlmtrace
# --check clean), log2 latency histograms + SLO burn accounting through
# tlmsum, postmortem capsules at every failure edge, heartbeat
# trace-attribution, and the live /status.json + /metrics endpoint
test-obs:
	$(CPU_ENV) $(PY) -m pytest tests/test_obs.py tests/test_obs_plane.py -q

# the concurrency-correctness suite (round 19, psrrace): lockdep unit
# tests + the watchdog defer-interrupt-while-locked regression under
# PYPULSAR_TPU_LOCKDEP=strict, the survey/multihost suites re-run
# strict (any acquisition-order cycle raises), then the quick seeded
# interleaving harness (claim/adopt + watchdog interrupt + prefetch
# concurrently, seeded lock-boundary pauses, byte-parity + zero
# violations asserted; committed record RACE_r01.json) — the
# slow-marked long-seed twin is tests/test_lockdep.py -m slow
test-race:
	PYPULSAR_TPU_LOCKDEP=strict $(CPU_ENV) $(PY) -m pytest tests/test_lockdep.py -q
	PYPULSAR_TPU_LOCKDEP=strict $(CPU_ENV) $(PY) -m pytest tests/test_multihost.py tests/test_survey.py -q -k "stall or deadline or watchdog or adopt or cede or prefetch"
	$(CPU_ENV) $(PY) bench.py --race --quick

# the multi-host fleet suite (round 18): fencing-token monotonicity +
# stale-write rejection, double-adoption single-winner, netstall
# split-brain cede, orphan adoption resuming byte-exactly, surplus
# hosts as adopters, torn shared-manifest tails, and the M-process CLI
# SIGKILL/adopt integration (spawn-probe gated) — plus the slow-marked
# every-stage-boundary kill sweep
test-multihost:
	$(CPU_ENV) $(PY) -m pytest tests/test_multihost.py -q
	$(CPU_ENV) $(PY) -m pytest tests/test_multihost.py -q -m slow -k sigkill

# the seeded chaos harness (bounded time: --quick geometry, seeded
# spray + one armed fault per family, resumed until complete, byte
# parity vs a clean run asserted) — the committed record is
# CHAOS_r01.json; the pytest-scale twin is marked `slow` so tier-1
# (-m 'not slow') stays bounded
test-chaos:
	$(CPU_ENV) $(PY) bench.py --chaos --quick
	$(CPU_ENV) $(PY) -m pytest tests/test_survey.py -q -m slow -k chaos

# the streaming-daemon suite (round 23): multi-tenant admission +
# token-bucket quotas, priority/quota-ordered overload shedding with a
# trace-reconstructible shed trail, guard hysteresis, the daemon fault
# points, journal replay after kill -9 — then the full soak harness
# (overload storm + chaos spray + SIGKILL'd subprocess + SIGTERM
# drain, byte-parity vs a batch reference asserted; the committed
# record is SOAK_r01.json, the pytest-scale twin is marked `slow`)
test-daemon:
	$(CPU_ENV) $(PY) -m pytest tests/test_daemon.py -q
	$(CPU_ENV) $(PY) bench.py --daemon-soak --quick
	$(CPU_ENV) $(PY) -m pytest tests/test_daemon.py -q -m slow -k soak

# the batch-broker suite (round 24): cross-observation coalescing
# semantics (budget close, SLO-pressure window collapse, party early
# close), the multi-series fold kernel's bitwise parity, brokered-fleet
# artifacts byte-identical to the PYPULSAR_TPU_BROKER=0 reference with
# real fusion proven by counters, batchmate fault isolation, and kill +
# resume mid-coalesce re-running only unvalidated stages
test-broker:
	$(CPU_ENV) $(PY) -m pytest tests/test_broker.py -q

# the candidate data plane suite (round 25): fenced store appends
# (stale-token writers rejected before touching the file), kill -9
# mid-append + re-publish yielding exactly-once records, torn-tail
# tolerance, pre/post-compaction query identity, two racing hosts over
# one store, the cross-obs candsift's harmonic clustering +
# known-source veto, the cands CLI, the /candidates endpoint, and the
# scheduler's terminal-edge ingest
test-candstore:
	$(CPU_ENV) $(PY) -m pytest tests/test_candstore.py -q

# the data-integrity suite: the checked-in corrupted-fixture corpus
# against every reader, salvage/scrub/finite-gate contracts, the
# degrade-vs-quarantine survey policy, and the acceptance-scale reader
# fuzz (500 seeded mutations per format, marked `slow` so tier-1 runs
# only the 60-mutation slice) — the committed record is CORRUPT_r01.json
test-corruption:
	$(CPU_ENV) $(PY) -m pytest tests/test_dataguard.py -q
	$(CPU_ENV) $(PY) -m pytest tests/test_dataguard.py -q -m slow -k fuzz

# the auto-tuning suite (round 17): knob-registry precedence (env >
# cache > default for every knob), cache durability (corrupt rebuild,
# key-component re-search, concurrent writers), bounded deterministic
# search, and the science-invariance gate (candidate/.pfd artifacts
# byte-identical across tuned configs — docs/ARCHITECTURE.md
# "Auto-tuning")
test-tune:
	$(CPU_ENV) $(PY) -m pytest tests/test_tune.py -q
	$(CPU_ENV) $(PY) -m pytest tests/test_obs.py -q -k "autotuning"

# the survey orchestrator suite: fleet-vs-serial byte parity, device
# lease exclusivity / host overlap, kill+resume at every stage
# boundary, quarantine, gang-lease placement (docs/ARCHITECTURE.md
# "Survey orchestrator" / "Scale-out") — plus the DM-sharded
# sweep->accel handoff parity tests that gang-leases place
test-survey:
	$(CPU_ENV) $(PY) -m pytest tests/test_survey.py -q
	$(CPU_ENV) $(PY) -m pytest tests/test_accel_pipeline.py -q -k "sharded or lease"

dryrun:
	$(CPU_ENV) $(PY) -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

bench:
	$(PY) bench.py

bench-quick:
	$(PY) bench.py --quick

# quick bench with a JSONL telemetry trace, then its tlmsum breakdown
# (stage wall %, H2D/D2H byte totals, chunk counts, device snapshot)
bench-telemetry:
	$(PY) bench.py --quick --telemetry bench_telemetry.jsonl
	$(PY) -m pypulsar_tpu.cli tlmsum bench_telemetry.jsonl

bench-ab:
	$(PY) bench.py --ab

bench-accel:
	$(PY) bench.py --accel

# the round-6 A/B in one command: configs[4] through the streamed
# sweep->accel handoff vs the classic .dat chain (walls + sift parity ->
# BENCH_r06_configs4.json), then the committed (r,z) roofline
bench-accel-pipeline:
	$(PY) tools/run_configs4.py --stream --ab-stream --keep
	$(PY) tools/accel_roofline.py

# the fold pipeline suite: batched-vs-serial archive parity (byte
# identical), refinement vs a refold grid, kill/resume, OOM halving,
# DM-group slicing (docs/ARCHITECTURE.md "Fold pipeline")
test-fold:
	$(CPU_ENV) $(PY) -m pytest tests/test_fold_pipeline.py -q

# engine throughput + the batched candidate-fold pipeline A/B
# (foldbatch vs the serial per-candidate prepfold loop)
bench-fold:
	$(PY) bench.py --fold

# the observability-plane overhead A/B (round 21): instrumentation-off
# vs flight-recorder-only vs full telemetry on the toy sweep->accel
# fleet — candidates byte-checked identical, full overhead asserted
# <= 5% in-process -> OBS_r01.json (the committed record)
bench-obs: test-obs
	$(CPU_ENV) $(PY) bench.py --obs-overhead --quick --out OBS_r01.json

# the survey orchestrator A/B: serial per-observation chain vs the
# fleet scheduler (host/device overlap) on 4 toy observations
bench-survey:
	$(PY) bench.py --survey --out BENCH_r08_survey.json

# multi-chip (round 11): the sharded sweep->accel parity suite + the
# k-device orchestrator A/B (gang-leases, fleet-parallel vs gang
# placement, artifacts byte-checked against the serial AND 1-device
# runs) on the 8-virtual-device CPU recipe -> BENCH_r09
bench-multichip:
	$(CPU_ENV) $(PY) -m pytest tests/test_accel_pipeline.py -q -k "sharded or lease"
	$(CPU_ENV) $(PY) -m pytest tests/test_survey.py -q -k "gang"
	$(CPU_ENV) $(PY) bench.py --survey --devices 4 --out BENCH_r09_multichip.json

# multi-host fleet (round 18): the coordination-plane suite, then the
# 3-process harness — clean fleet A/B vs the 1-host serial chain
# (with the round-21 live --status-port endpoint scraped mid-fleet), a
# host SIGKILL'd mid-sweep with fenced adoption by survivors, byte
# parity both legs, final resume re-runs zero stages, and the kill
# leg's traces tlmtrace-stitched with the adoption asserted visible as
# a lane handover -> BENCH_r13_multihost.json + HOSTCHAOS_r01.json +
# OBS_trace_r01.json
bench-multihost-fleet:
	$(CPU_ENV) $(PY) -m pytest tests/test_multihost.py -q
	$(CPU_ENV) $(PY) bench.py --multihost --quick --out BENCH_r13_multihost.json --hostchaos-out HOSTCHAOS_r01.json

# spectral fusion (round 15): the fused-path parity suite (stitched
# byte-identity at awkward geometries + mesh + kill/resume, decimate
# circular-reference + counters), then the 3-way pipeline A/B (.dat
# chain vs streamed handoff vs --spectral fused, plus the opt-in
# decimate leg) -> BENCH_r10_specfuse.json
bench-specfuse:
	$(CPU_ENV) $(PY) -m pytest tests/test_accel_pipeline.py -q -k "spectral"
	$(CPU_ENV) $(PY) bench.py --accel --spectral --out BENCH_r10_specfuse.json

# tree dedispersion (round 16): the tree-engine parity suite (exact
# snap, mesh bit-identity, chain byte-identity, kill/resume), then the
# three-engine A/B at the production DM-count geometry — SNR parity
# asserted in-process, adds/cell from tools/dedisp_roofline.py as the
# gate -> BENCH_r11_tree.json
bench-tree:
	$(CPU_ENV) $(PY) -m pytest tests/test_sweep.py tests/test_accel_pipeline.py -q -k "tree"
	$(CPU_ENV) $(PY) bench.py --dedisp-tree --out BENCH_r11_tree.json

# auto-tuning (round 17): the tune suite, then the bounded-search A/B
# at 2 geometries (trials <= budget, tuned >= hand-picked baseline,
# second consult = zero trials via tune.cache_hit, candidate artifacts
# byte-identical across tuned configs) -> BENCH_r12_tune.json
bench-tune: test-tune
	$(CPU_ENV) $(PY) bench.py --tune --out BENCH_r12_tune.json

# the round-22 compilation-plane A/B: cold-vs-warm compile counters at
# 3 toy geometries (warm legs must compile NOTHING), bucket-ladder
# collapse, cross-process persistent-cache hits, byte-identical
# artifacts throughout, and the fleet warm-pool precompile span
# overlapping another observation's device span
bench-compile:
	$(CPU_ENV) $(PY) -m pytest tests/test_compile.py -q
	$(CPU_ENV) $(PY) bench.py --compile --out BENCH_r17_compile.json

# the round-24 batch-broker A/B: >=4 small same-geometry observations,
# brokered (lanes + wide window) vs per-obs dispatch, gated on
# STRUCTURAL counters — coalesce factor >= 2, fused dispatches <= half
# the baseline's, zero extra compile misses, artifacts byte-identical
# (CPU-toy walls are labeled, not gated)
bench-broker: test-broker
	$(CPU_ENV) $(PY) bench.py --broker --out BENCH_r19_broker.json

# the round-25 candidate-plane A/B: the same pulsar injected at 3
# epochs + per-epoch noise through the real fleet ingest — store-on vs
# PYPULSAR_TPU_CANDSTORE=0 with per-obs artifacts byte-identical,
# cross-obs dedup factor asserted > 1 (the pulsar's epochs collapse to
# one cluster), kill -9 mid-append + resume leaving exactly-once
# books, and query results identical pre/post compaction
bench-candplane: test-candstore
	$(CPU_ENV) $(PY) bench.py --candplane --out BENCH_r20_candplane.json

native:
	$(PY) -c "from pypulsar_tpu import native; assert native.available(); print('native codec OK')"

clean:
	find . -name '__pycache__' -type d -exec rm -rf {} + 2>/dev/null; \
	rm -f pypulsar_tpu/native/libpsrcodec.so
